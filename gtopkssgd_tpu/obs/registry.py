"""Cross-run regression registry: append-only per-workspace run memory.

A single run's record stream answers "what happened in THIS run"; nothing
in the repo remembers the run before it, so a 20% throughput regression
or a comm model drifting across a redeploy is invisible until someone
diffs two metrics.jsonl files by hand. This module gives a workspace that
memory: every run appends ONE line (manifest header + end-of-run summary
stats) to ``runs.jsonl`` in a registry directory, and the report CLI
reads it back offline:

    python -m gtopkssgd_tpu.obs.report history REGISTRY_DIR
    python -m gtopkssgd_tpu.obs.report regress RUN --registry REGISTRY_DIR

``history`` prints the trend table (keyed by config_hash — only runs of
the same configuration are comparable); ``regress`` summarizes the
current run from its shards, picks the most recent registry entry with
the same config_hash as baseline, and applies rtol-per-field drift
checks with the ``report gate`` exit contract: 0 within tolerance, 1
regression, 2 usage/no-baseline. Entries are plain JSON lines — the
registry needs no daemon, survives partial writes (bad lines are
skipped and counted), and merges across machines with ``cat``.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

REGISTRY_NAME = "runs.jsonl"

# Manifest keys copied into each entry: config_hash keys comparability,
# the rest make a registry line readable without the run directory.
# lineage_id/resize_epoch (elastic runs only — resilience/elastic.py)
# join the pre/post segments of a resized run into ONE trajectory even
# though the config_hash changes with --nworkers.
_MANIFEST_KEYS = ("config_hash", "git_sha", "dnn", "dataset",
                  "compression", "density", "wire_codec", "nworkers",
                  "batch_size", "seed", "lineage_id", "resize_epoch")

# Regression checks: (field, rtol, atol). Gate tolerance semantics —
# FAIL when |current - baseline| > atol + rtol*|baseline|. Throughput
# and loss are noisy (25%); comm ratio noisier still; fitted alpha/beta
# tolerate a full 2x before flagging (factor-level drift is what the
# live comm_model_drift rule exists for — the registry catches the
# slow cross-run creep); wire bytes/step is deterministic (10% covers
# codec padding jitter only); recall floor gets an absolute slack so a
# floor of 0.0 doesn't make the check vacuous. The two memwatch fields
# (--obs-mem runs only) are the space plane: peak-HBM is an analytical
# estimate that moves only when the program or its sharding does (10%
# covers XLA temp-allocation jitter across compiler versions), and
# recompile_count is exact — ANY cross-run change in how often the jit
# cache grew under the same config is a regression. overlap_frac (the
# measured fraction of comm hidden under compute/select, trace-derived)
# gets a purely absolute 0.1 slack: it lives in [0, 1] and a serial
# baseline of 0.0 must still bound an overlapped current run — a
# pipelined run whose overlap silently collapsed back to serial is
# exactly the regression this line exists to catch. n_buckets is exact:
# the DP re-deciding B under the same config means the cost model moved.
REGRESS_CHECKS: Tuple[Tuple[str, float, float], ...] = (
    ("steps_per_sec", 0.25, 0.0),
    ("loss_last", 0.25, 0.0),
    ("mean_comm_ratio", 0.50, 0.0),
    ("alpha_ms", 1.00, 0.0),
    ("beta_gbps", 1.00, 0.0),
    ("recall_floor", 0.25, 0.05),
    ("wire_bytes_per_step", 0.10, 0.0),
    ("peak_hbm_bytes", 0.10, 0.0),
    ("recompile_count", 0.0, 0.0),
    ("overlap_frac", 0.0, 0.10),
    ("n_buckets", 0.0, 0.0),
    # wait_frac (mean share of each rank's step wall spent blocked at
    # collectives, from the critpath plane) gets the same purely
    # absolute 0.1 slack as overlap_frac and for the same reason: it
    # lives in [0, 1] and a clean baseline of 0.0 must still bound a
    # current run that started skewing.
    ("wait_frac", 0.0, 0.10),
    # goodput_frac (productive share of the run's wall, from the
    # goodput ledger's final summary — obs/goodput.py) is the single
    # number the whole badput taxonomy rolls up to; purely absolute
    # 0.1 slack for the same [0, 1] reason as the two above — a run
    # whose productive share quietly dropped ten points under the same
    # config is the regression this line pins.
    ("goodput_frac", 0.0, 0.10),
    # hindcast_err_x (forecast plane, obs/forecast.py: predicted vs
    # measured step time on the run itself) lives near 1.0 by
    # construction; a purely absolute 0.5 slack pins it — a model whose
    # self-explanation quietly worsened past half a turn under the same
    # config is a forecast regression, the offline mirror of the live
    # forecast_drift rule.
    ("hindcast_err_x", 0.0, 0.50),
)

# String-valued stats checked for EXACT equality (the numeric loop's
# finiteness gate would silently skip them — a chosen pipeline that
# flips serial<->overlap under the same config is a plan regression,
# not noise; the modal critical stage moving compute<->wait under the
# same config means the run's bottleneck moved, which is exactly what
# the critpath plane exists to flag). The forecast plane's per-target
# recommendations (forecast_rec_p256 etc.) join this set dynamically in
# regress(): a silent flip of the recommended P=256 plan under the same
# config must fail the gate.
REGRESS_EXACT_STR: Tuple[str, ...] = ("pipeline", "crit_stage_modal")


def _finite(x: Any) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def registry_path(registry_dir: str) -> str:
    return os.path.join(registry_dir, REGISTRY_NAME)


def _cell(v: Any) -> str:
    """Table cell: report._fmt for numbers, "-" for absent stats."""
    if _finite(v):
        from gtopkssgd_tpu.obs.report import _fmt
        return _fmt(float(v))
    return "-" if v is None else str(v)


def run_summary(records: Sequence[Dict[str, Any]]
                ) -> Optional[Dict[str, Any]]:
    """Distill one run's record stream into a registry entry: manifest
    subset + summary stats. Stats a run didn't produce (no calib
    records, no audits) are simply absent — ``regress`` treats a field
    missing on both sides as not-applicable, present-then-vanished as a
    failure. Returns None when the stream has no manifest (nothing to
    key comparisons on)."""
    manifest = None
    trains: List[Dict[str, Any]] = []
    last_calib = None
    last_plan = None
    final_status = None
    recall_floor = None
    wire_sum, wire_n = 0.0, 0
    ratio_sum, ratio_n = 0.0, 0
    ofrac_sum, ofrac_n = 0.0, 0
    wait_sum, wait_n = 0.0, 0
    crit_counts: Dict[str, int] = {}
    saw_memwatch = False
    recompile_count = 0
    last_goodput = None
    last_forecast = None
    for rec in records:
        kind = rec.get("kind")
        if kind == "manifest" and manifest is None:
            manifest = rec
        elif kind == "train":
            trains.append(rec)
        elif kind == "calib":
            last_calib = rec
        elif kind == "plan":
            last_plan = rec
        elif kind in ("compile", "mem"):
            # memwatch (--obs-mem) was on; recompile_count stays an
            # explicit 0 in that case so regress can pin it exactly.
            saw_memwatch = True
            if _finite(rec.get("recompile_count")):
                recompile_count = max(recompile_count,
                                      int(rec["recompile_count"]))
        elif kind == "obs":
            recall = rec.get("audit_recall")
            if _finite(recall) and recall >= 0:
                recall_floor = (recall if recall_floor is None
                                else min(recall_floor, recall))
            wb = rec.get("wire_bytes")
            if _finite(wb) and wb > 0:
                wire_sum += float(wb)
                wire_n += 1
        elif kind == "attr":
            # measured comm share of the dispatch — the ledger's
            # numerator; ratio vs total is schedule-independent
            tc, tt = rec.get("t_comm_us"), rec.get("t_total_us")
            if _finite(tc) and _finite(tt) and tt > 0:
                ratio_sum += float(tc) / float(tt)
                ratio_n += 1
            if _finite(rec.get("overlap_frac")):
                ofrac_sum += float(rec["overlap_frac"])
                ofrac_n += 1
        elif kind == "critpath":
            # per-rank stage-interval plane (obs/critpath.py): the mean
            # blocked share and the modal LOCAL critical stage across
            # all shipped records — cross-run comparable without the
            # fleet join.
            if _finite(rec.get("wait_frac")):
                wait_sum += float(rec["wait_frac"])
                wait_n += 1
            cs = rec.get("crit_stage")
            if isinstance(cs, str) and cs:
                crit_counts[cs] = crit_counts.get(cs, 0) + 1
        elif kind == "goodput":
            # cumulative ledger records (obs/goodput.py): the LAST one
            # is the run's accounting, so it alone feeds the entry.
            last_goodput = rec
        elif kind == "forecast":
            # scale-out forecast records (obs/forecast.py): the LAST
            # one carries the settled hindcast error and per-P
            # recommendations, so it alone feeds the entry.
            last_forecast = rec
        elif kind == "recovery" and rec.get("final_status") is not None:
            final_status = rec.get("final_status")
    if manifest is None:
        return None
    entry: Dict[str, Any] = {"time": manifest.get("time")}
    for key in _MANIFEST_KEYS:
        if manifest.get(key) is not None:
            entry[key] = manifest[key]
    stats: Dict[str, Any] = {}
    steps = [r for r in trains
             if _finite(r.get("step")) and _finite(r.get("time"))]
    if len(steps) >= 2:
        dt = steps[-1]["time"] - steps[0]["time"]
        ds = steps[-1]["step"] - steps[0]["step"]
        if dt > 0 and ds > 0:
            stats["steps_per_sec"] = round(ds / dt, 6)
    if trains:
        stats["n_steps"] = trains[-1].get("step")
        loss = trains[-1].get("loss")
        if _finite(loss):
            stats["loss_last"] = round(float(loss), 6)
    if ratio_n:
        stats["mean_comm_ratio"] = round(ratio_sum / ratio_n, 6)
    if last_calib is not None:
        if _finite(last_calib.get("alpha_fit_ms")):
            stats["alpha_ms"] = last_calib["alpha_fit_ms"]
        if _finite(last_calib.get("beta_fit_gbps")):
            stats["beta_gbps"] = last_calib["beta_fit_gbps"]
        # Per-axis fits ride the calib record under dotted keys
        # (alpha_ms.dcn, beta_gbps.ici, ...); carry them verbatim so
        # regress can pin each measured hop, not just the blend.
        for field in sorted(last_calib):
            if ((field.startswith("alpha_ms.")
                 or field.startswith("beta_gbps."))
                    and _finite(last_calib[field])):
                stats[field] = last_calib[field]
    if recall_floor is not None:
        stats["recall_floor"] = round(float(recall_floor), 6)
    if wire_n:
        stats["wire_bytes_per_step"] = round(wire_sum / wire_n, 2)
    if _finite(manifest.get("peak_hbm_bytes")):
        stats["peak_hbm_bytes"] = manifest["peak_hbm_bytes"]
    if saw_memwatch:
        stats["recompile_count"] = recompile_count
    if ofrac_n:
        stats["overlap_frac"] = round(ofrac_sum / ofrac_n, 6)
    if wait_n:
        stats["wait_frac"] = round(wait_sum / wait_n, 6)
    if last_goodput is not None:
        if _finite(last_goodput.get("goodput_frac")):
            stats["goodput_frac"] = round(
                float(last_goodput["goodput_frac"]), 6)
        if _finite(last_goodput.get("other_frac")):
            stats["other_frac"] = round(
                float(last_goodput["other_frac"]), 6)
    if last_forecast is not None:
        # Forecast plane: the hindcast error (numeric drift check) plus
        # the recommended plan string at each P target
        # (forecast_rec_p{P}, exact-string checked in regress() — a
        # calibrated artifact flipping the P=256 recommendation is a
        # DELIBERATE change that must fail a same-config gate).
        if _finite(last_forecast.get("hindcast_err_x")):
            stats["hindcast_err_x"] = round(
                float(last_forecast["hindcast_err_x"]), 6)
        if _finite(last_forecast.get("crossover_p")):
            stats["forecast_crossover_p"] = int(
                last_forecast["crossover_p"])
        for field in sorted(last_forecast):
            if (field.startswith("rec_p") and field[5:].isdigit()
                    and isinstance(last_forecast[field], str)):
                stats["forecast_" + field] = last_forecast[field]
    if crit_counts:
        # Modal stage; ties break by critpath.STAGES order (inlined as
        # a sort over the fixed tuple to keep the registry stdlib-only).
        order = ("compute", "select", "comm", "wait")
        stats["crit_stage_modal"] = max(
            sorted(crit_counts, key=lambda s: order.index(s)
                   if s in order else len(order)),
            key=lambda s: crit_counts[s])
    # Plan-shape stats: the chosen pipeline (plan record wins — it is
    # the decision as executed; the manifest stamp is the fallback for
    # runs without a planner) and the DP's bucket count, so regress can
    # pin both exactly across runs of the same config.
    pipeline = (last_plan or {}).get("pipeline") or manifest.get("pipeline")
    if pipeline is not None:
        stats["pipeline"] = str(pipeline)
    bucket_ks = manifest.get("bucket_ks")
    if isinstance(bucket_ks, (list, tuple)) and bucket_ks:
        stats["n_buckets"] = len(bucket_ks)
    if final_status is not None:
        stats["final_status"] = final_status
    entry["stats"] = stats
    return entry


def append_run(registry_dir: str, entry: Dict[str, Any]) -> str:
    """Append one entry (fsync'd — a registry line is the run's only
    cross-run trace, it must survive the process dying right after)."""
    os.makedirs(registry_dir, exist_ok=True)
    path = registry_path(registry_dir)
    with open(path, "a") as fh:
        fh.write(json.dumps(entry, sort_keys=True) + "\n")
        fh.flush()
        try:
            os.fsync(fh.fileno())
        except OSError:
            pass
    return path


def load_registry(registry_dir: str) -> Tuple[List[Dict[str, Any]], int]:
    """All parseable entries in file order, plus the count of bad lines
    (a torn write from a killed run must not poison the registry)."""
    path = registry_path(registry_dir)
    entries: List[Dict[str, Any]] = []
    bad = 0
    if not os.path.exists(path):
        return entries, bad
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(rec, dict):
                entries.append(rec)
            else:
                bad += 1
    return entries, bad


def history_rows(entries: Sequence[Dict[str, Any]],
                 config_hash: Optional[str] = None
                 ) -> List[List[str]]:
    """Trend-table rows (newest last) for ``report history``; filtered
    to one config_hash when given. The filter follows elastic lineage:
    an entry whose lineage_id matches any hash-matched entry's is kept
    too, so a resized run's pre/post segments (different --nworkers,
    hence different config_hash) render as one trajectory."""
    lineages = {e.get("lineage_id") for e in entries
                if config_hash and e.get("config_hash") == config_hash
                and e.get("lineage_id")}
    rows = []
    for e in entries:
        if config_hash and e.get("config_hash") != config_hash and not (
                e.get("lineage_id") and e.get("lineage_id") in lineages):
            continue
        stats = e.get("stats") or {}
        # Compact per-axis fit cell: "dcn:21.9/2.1 ici:0.1/1600" —
        # alpha_ms/beta_gbps per measured axis; "-" pre-linkmap.
        ax_names = sorted({f.split(".", 1)[1] for f in stats
                           if f.startswith(("alpha_ms.", "beta_gbps."))})
        axes_cell = " ".join(
            f"{a}:{_cell(stats.get('alpha_ms.' + a))}"
            f"/{_cell(stats.get('beta_gbps.' + a))}"
            for a in ax_names) or "-"
        rows.append([
            str(e.get("config_hash", "?"))[:16],
            str(e.get("git_sha", "?"))[:10],
            _cell(stats.get("n_steps")),
            _cell(stats.get("steps_per_sec")),
            _cell(stats.get("loss_last")),
            _cell(stats.get("mean_comm_ratio")),
            _cell(stats.get("alpha_ms")),
            _cell(stats.get("beta_gbps")),
            axes_cell,
            _cell(stats.get("recall_floor")),
            _cell(stats.get("wire_bytes_per_step")),
            _cell(stats.get("peak_hbm_bytes")),
            _cell(stats.get("recompile_count")),
            str(stats.get("pipeline", "-")),
            _cell(stats.get("n_buckets")),
            _cell(stats.get("overlap_frac")),
            str(stats.get("crit_stage_modal", "-")),
            _cell(stats.get("wait_frac")),
            _cell(stats.get("goodput_frac")),
            _cell(stats.get("hindcast_err_x")),
            str(stats.get("forecast_rec_p256", "-")),
            # "lid8:epoch" for elastic runs — the join key that groups
            # a resized run's segments; "-" for classic runs.
            (f"{str(e['lineage_id'])[:8]}:{e.get('resize_epoch', 0)}"
             if e.get("lineage_id") else "-"),
            str(stats.get("final_status", "-")),
        ])
    return rows


HISTORY_HEADER = ["config", "git", "steps", "steps/s", "loss",
                  "comm_ratio", "alpha_ms", "beta_gbps", "axes",
                  "recall", "wireB/step", "peak_hbm", "recomp",
                  "pipeline", "B", "ovl_frac", "crit_stage",
                  "wait_frac", "goodput", "hindcast", "fc_p256",
                  "lineage", "status"]


def pick_baseline(entry: Dict[str, Any],
                  entries: Sequence[Dict[str, Any]],
                  allow_mismatch: bool = False
                  ) -> Optional[Dict[str, Any]]:
    """Most recent registry entry with the current run's config_hash
    (comparing runs of different configurations is apples-to-oranges —
    opt in explicitly with allow_mismatch). Elastic exception: an entry
    sharing the run's lineage_id is the SAME logical run on a different
    fleet size, so it baselines a post-resize segment without
    allow_mismatch — size-dependent fields (wire bytes, fits) drift and
    should be read with that in mind, but loss/recall continuity is
    exactly what the lineage join exists to check."""
    want = entry.get("config_hash")
    matches = [e for e in entries
               if want is not None and e.get("config_hash") == want]
    if matches:
        return matches[-1]
    lid = entry.get("lineage_id")
    kin = [e for e in entries
           if lid is not None and e.get("lineage_id") == lid]
    if kin:
        return kin[-1]
    if allow_mismatch and entries:
        return entries[-1]
    return None


def regress(entry: Dict[str, Any], baseline: Dict[str, Any]
            ) -> Tuple[List[List[str]], int]:
    """Field-by-field drift check of ``entry`` against ``baseline``
    under REGRESS_CHECKS. Returns (table rows, failure count). A field
    absent from both runs is skipped; absent from the baseline only is
    noted "new" (new instrumentation is not a regression); present in
    the baseline but vanished from the current run FAILS — a counter
    that silently disappears is exactly the kind of regression the
    registry exists to catch."""
    cur = entry.get("stats") or {}
    base = baseline.get("stats") or {}
    rows: List[List[str]] = []
    failures = 0
    # Per-axis alpha/beta stats (alpha_ms.<axis> / beta_gbps.<axis>,
    # from the calibrator's per-axis fits) are dynamic — the axis names
    # are the mesh's, not ours — so pin every one present on either
    # side at the same 2x rtol the blended fit gets: a silently
    # degraded hop fails the cross-run gate like any other field.
    axis_checks = tuple(
        (field, 1.00, 0.0)
        for field in sorted(set(cur) | set(base))
        if field.startswith(("alpha_ms.", "beta_gbps.")))
    for field, rtol, atol in REGRESS_CHECKS + axis_checks:
        have_cur, have_base = _finite(cur.get(field)), _finite(
            base.get(field))
        if not have_cur and not have_base:
            continue
        tol_s, status = "-", "ok"
        if not have_base:
            status = "new"
        elif not have_cur:
            status = "MISSING"
            failures += 1
        else:
            b, c = float(base[field]), float(cur[field])
            tol = atol + rtol * abs(b)
            tol_s = _cell(tol)
            if abs(c - b) > tol:
                status = "FAIL"
                failures += 1
        rows.append([field, _cell(base.get(field)), _cell(cur.get(field)),
                     tol_s, status])
    # Forecast recommendations are dynamic like the per-axis fits (one
    # per configured P target), so every forecast_rec_p* present on
    # either side joins the exact-string set: the recommended plan
    # flipping under the same config — a calibrated artifact repricing
    # the grid — must fail the gate, never slide through silently.
    forecast_checks = tuple(
        field for field in sorted(set(cur) | set(base))
        if field.startswith("forecast_rec_p"))
    for field in REGRESS_EXACT_STR + forecast_checks:
        b, c = base.get(field), cur.get(field)
        if b is None and c is None:
            continue
        if b is None:
            status = "new"
        elif c is None:
            status = "MISSING"
            failures += 1
        elif str(c) != str(b):
            status = "FAIL"
            failures += 1
        else:
            status = "ok"
        rows.append([field, "-" if b is None else str(b),
                     "-" if c is None else str(c), "exact", status])
    return rows, failures


REGRESS_HEADER = ["field", "baseline", "current", "tol", "status"]
