"""Host-side timeline export: one Chrome-trace/Perfetto JSON per run.

The device profiler answers "what did the accelerator do"; this module
answers "what was the HOST doing, and when did the run's health change" —
and puts both on the same clock. A ``TimelineRecorder`` collects

  * every closed ``Tracer`` span (io / dispatch / obs_read / final_sync,
    nested paths intact) as a duration event on the emitting thread's
    lane,
  * per-step telemetry as counter tracks (loss, achieved density,
    residual norm — Perfetto plots them as line graphs), and
  * anomaly events and watchdog stalls as instant markers,

then writes a standard ``traceEvents`` JSON (``--obs-timeline PATH``)
that chrome://tracing, Perfetto, or ``report timeline`` can open. A
device trace captured over the same steps carries identical span names
(the Tracer emits both), so the two files line up by construction.

``timeline_from_records`` rebuilds a (coarser) timeline offline from a
run's metrics.jsonl — markers and counters at their recorded wall-clock
times — for runs that didn't pass the flag; ``validate_timeline`` is the
schema check the tests and the report CLI share.

All timestamps are wall-clock µs (chrome-trace convention); span starts
are derived from the Tracer's perf_counter clock against a base pair
sampled at recorder construction, so spans and markers share one axis.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_META = ("process_name", "thread_name", "process_sort_index")


class TimelineRecorder:
    """Thread-safe accumulator for one run's host timeline."""

    def __init__(self, rank: int = 0, label: str = "trainer"):
        self.rank = rank
        self.label = label
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._base_wall = time.time()
        self._base_perf = time.perf_counter()
        self._tids: Dict[int, int] = {}

    # ------------------------------------------------------------- clocks
    def _now_us(self) -> float:
        return time.time() * 1e6

    def _perf_to_us(self, t_perf: float) -> float:
        return (self._base_wall + (t_perf - self._base_perf)) * 1e6

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = len(self._tids)
                self._events.append({
                    "ph": "M", "name": "thread_name", "pid": self.rank,
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                })
            return tid

    # ------------------------------------------------------------ emitters
    def span_sink(self, path: str, t0_perf: float, dur_s: float) -> None:
        """Tracer sink: one duration event per closed span. Signature is
        the Tracer's ``sink`` contract (path, perf_counter start,
        seconds)."""
        tid = self._tid()
        with self._lock:
            self._events.append({
                "ph": "X", "name": path, "cat": "host_span",
                "ts": self._perf_to_us(t0_perf), "dur": dur_s * 1e6,
                "pid": self.rank, "tid": tid,
            })

    def instant(self, name: str, args: Optional[Dict[str, Any]] = None,
                ts_us: Optional[float] = None) -> None:
        """Moment marker (anomaly event, watchdog stall, epoch boundary)."""
        tid = self._tid()
        with self._lock:
            self._events.append({
                "ph": "i", "s": "p", "name": name, "cat": "marker",
                "ts": self._now_us() if ts_us is None else ts_us,
                "pid": self.rank, "tid": tid,
                **({"args": args} if args else {}),
            })

    def counter(self, name: str, values: Dict[str, float],
                ts_us: Optional[float] = None) -> None:
        """Counter track sample — Perfetto renders a line graph per key."""
        vals = {k: float(v) for k, v in values.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
                and float(v) == float(v)}  # NaN samples break the track
        if not vals:
            return
        with self._lock:
            self._events.append({
                "ph": "C", "name": name,
                "ts": self._now_us() if ts_us is None else ts_us,
                "pid": self.rank, "tid": 0, "args": vals,
            })

    # -------------------------------------------------------------- output
    def to_doc(self) -> dict:
        with self._lock:
            events = list(self._events)
        meta = [{"ph": "M", "name": "process_name", "pid": self.rank,
                 "args": {"name": f"host {self.label} rank {self.rank}"}}]
        meta += [e for e in events if e.get("ph") == "M"]
        body = sorted((e for e in events if e.get("ph") != "M"),
                      key=lambda e: e.get("ts", 0.0))
        return {"traceEvents": meta + body, "displayTimeUnit": "ms"}

    def write(self, path: str) -> str:
        """Write the timeline JSON; a directory path gets timeline.json
        appended. Returns the file written."""
        if os.path.isdir(path) or path.endswith(os.sep):
            path = os.path.join(path, "timeline.json")
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(self.to_doc(), fh)
            fh.write("\n")
        return path


# ----------------------------------------------------- offline + validate

# metrics.jsonl kinds rendered as counter tracks offline, and the fields
# each contributes (a missing field is just skipped).
_COUNTER_KINDS = {
    "train": ("loss", "throughput"),
    "obs": ("achieved_density", "residual_norm", "grad_norm_post", "tau"),
    "goodput": ("goodput_frac", "other_frac"),
}
_MARKER_KINDS = ("event", "stall")


def timeline_from_records(records: List[dict],
                          label: str = "run") -> dict:
    """Rebuild a coarse timeline from metrics.jsonl records: counter
    samples for train/obs numerics and instant markers for event/stall
    records, at their recorded wall-clock times. Span durations are not
    reconstructed (the jsonl carries window means, not start times) —
    use --obs-timeline for the live span view.

    ``critpath`` records (obs/critpath.py) additionally get per-rank
    STAGE LANES: one duration event per stage segment on a dedicated
    lane per rank, anchored so each rank's step window ends at its
    record's wall-clock time, with ``args.critical`` marking the
    segments the step's global (cross-rank) critical path runs
    through — the Perfetto view of "which rank, which stage"."""
    events: List[dict] = [{
        "ph": "M", "name": "process_name", "pid": 0,
        "args": {"name": f"host {label} (from metrics.jsonl)"},
    }, {
        "ph": "M", "name": "thread_name", "pid": 0, "tid": 0,
        "args": {"name": "records"},
    }]
    body: List[dict] = []
    # ---- critpath stage lanes: group records by step across ranks so
    # the global chain can flag the critical segments.
    crit_by_step: Dict[float, Dict[int, dict]] = {}
    for rec in records:
        if (rec.get("kind") == "critpath"
                and isinstance(rec.get("step"), (int, float))
                and isinstance(rec.get("time"), (int, float))
                and isinstance(rec.get("segments"), list)):
            crit_by_step.setdefault(
                float(rec["step"]), {})[int(rec.get("rank", 0))] = rec
    if crit_by_step:
        # Lazy import: keeps the module's offline path stdlib-only for
        # runs without a critpath plane.
        from gtopkssgd_tpu.obs import critpath as _critpath
        lanes_seen: set = set()
        for step in sorted(crit_by_step):
            per_rank = crit_by_step[step]
            res = _critpath.critical_path(
                {r: rec["segments"] for r, rec in per_rank.items()})
            chain = res.get("chain", [])
            for r in sorted(per_rank):
                rec = per_rank[r]
                tid = 100 + r  # one stage lane per rank, after tid 0
                if tid not in lanes_seen:
                    lanes_seen.add(tid)
                    events.append({
                        "ph": "M", "name": "thread_name", "pid": 0,
                        "tid": tid,
                        "args": {"name": f"critpath rank {r}"}})
                # Anchor: the record lands when the step's capture
                # ends, so the rank's window [0, wall] maps to
                # [time - wall, time] on the shared wall-clock axis.
                wall = float(rec.get("wall_us", 0.0))
                t_end = float(rec["time"]) * 1e6
                for seg in rec["segments"]:
                    t0 = float(seg.get("t0_us", 0.0))
                    t1 = float(seg.get("t1_us", 0.0))
                    if t1 <= t0:
                        continue
                    critical = any(
                        p["rank"] == r and p["stage"] == seg.get("stage")
                        and min(float(p["t1_us"]), t1)
                        - max(float(p["t0_us"]), t0) > 1e-6
                        for p in chain)
                    body.append({
                        "ph": "X", "name": str(seg.get("stage")),
                        "cat": "critpath",
                        "ts": t_end - wall + t0, "dur": t1 - t0,
                        "pid": 0, "tid": tid,
                        "args": {"step": step, "critical": critical,
                                 "crit_stage": res.get("crit_stage")},
                    })
    # ---- per-link lanes: each linkmap record's carved per-round
    # intervals (obs/linkmap.py) become duration events on one lane per
    # (axis, peer-pair) link, anchored like the critpath lanes so the
    # observing rank's comm window ends at the record's wall time — the
    # Perfetto view of WHICH hop each round's time went to.
    # Link lanes allocate above the per-rank stage lanes, which occupy
    # [100, 100 + max rank]: a fixed 200 base aliased lanes on fleets
    # with >= 100 ranks (rank 100's stage lane IS tid 200).
    _max_rank = max((r for per_rank in crit_by_step.values()
                     for r in per_rank), default=-1)
    link_base = max(200, 101 + _max_rank)
    link_tids: Dict[str, int] = {}
    for rec in records:
        if (rec.get("kind") != "linkmap"
                or not isinstance(rec.get("time"), (int, float))
                or not isinstance(rec.get("rounds"), list)):
            continue
        rounds = [rd for rd in rec["rounds"]
                  if isinstance(rd, dict)
                  and isinstance(rd.get("t_ms"), (int, float))
                  and not isinstance(rd.get("t_ms"), bool)]
        total_us = sum(float(rd["t_ms"]) for rd in rounds) * 1e3
        t_cursor = float(rec["time"]) * 1e6 - total_us
        for rd in rounds:
            dur = float(rd["t_ms"]) * 1e3
            try:
                lo, hi = sorted((int(rd.get("src")), int(rd.get("dst"))))
            except (TypeError, ValueError):
                t_cursor += dur
                continue
            key = f"{rd.get('axis', '?')}:{lo}-{hi}"
            tid = link_tids.get(key)
            if tid is None:
                tid = link_tids[key] = link_base + len(link_tids)
                events.append({
                    "ph": "M", "name": "thread_name", "pid": 0,
                    "tid": tid, "args": {"name": f"link {key}"}})
            if dur > 0:
                body.append({
                    "ph": "X", "name": key, "cat": "linkmap",
                    "ts": t_cursor, "dur": dur, "pid": 0, "tid": tid,
                    "args": {"step": rec.get("step"),
                             "round": rd.get("round"),
                             "rank": rec.get("rank", 0),
                             "axis": rd.get("axis")},
                })
            t_cursor += dur
    for rec in records:
        kind = rec.get("kind")
        ts = rec.get("time")
        if not isinstance(ts, (int, float)):
            continue
        ts_us = float(ts) * 1e6
        if kind in _COUNTER_KINDS:
            vals = {f: float(rec[f]) for f in _COUNTER_KINDS[kind]
                    if isinstance(rec.get(f), (int, float))
                    and not isinstance(rec.get(f), bool)
                    and float(rec[f]) == float(rec[f])}
            if vals:
                body.append({"ph": "C", "name": kind, "ts": ts_us,
                             "pid": 0, "tid": 0, "args": vals})
            if kind == "goodput":
                # Badput track: cumulative seconds per category
                # (obs/goodput.py taxonomy) as one stacked counter —
                # the Perfetto view of WHERE non-productive wall
                # accrues over the run.
                from gtopkssgd_tpu.obs import goodput as _goodput
                bad = {c: float(rec[f"{c}_s"])
                       for c in _goodput.BADPUT + ("other",)
                       if isinstance(rec.get(f"{c}_s"), (int, float))
                       and not isinstance(rec.get(f"{c}_s"), bool)}
                if bad:
                    body.append({"ph": "C", "name": "badput_s",
                                 "ts": ts_us, "pid": 0, "tid": 0,
                                 "args": bad})
        elif kind in _MARKER_KINDS:
            name = (f"{kind}:{rec.get('rule', '?')}" if kind == "event"
                    else kind)
            args = {k: v for k, v in rec.items()
                    if k in ("rule", "severity", "step", "value",
                             "threshold", "message")}
            body.append({"ph": "i", "s": "p", "name": name, "cat": "marker",
                         "ts": ts_us, "pid": 0, "tid": 0, "args": args})
    body.sort(key=lambda e: e["ts"])
    return {"traceEvents": events + body, "displayTimeUnit": "ms"}


def validate_timeline(doc: dict) -> List[str]:
    """Chrome-trace schema check: required keys per phase type and
    globally monotonic non-metadata timestamps. Returns problem strings
    (empty = valid) — shared by the tests and ``report timeline``."""
    problems: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    last_ts = None
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = e.get("ph")
        if ph is None or "name" not in e or "pid" not in e:
            problems.append(f"event {i}: missing ph/name/pid")
            continue
        if ph == "M":
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            problems.append(f"event {i} ({e.get('name')}): missing ts")
            continue
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(
                    f"event {i} ({e.get('name')}): X without dur >= 0")
        if last_ts is not None and ts < last_ts:
            problems.append(
                f"event {i} ({e.get('name')}): ts not monotonic "
                f"({ts} < {last_ts})")
        last_ts = ts
    return problems
