"""Tracing spans: one name, two timelines.

A ``Tracer.span("io")`` emits

  * a host-side duration into a TimingStats accumulator (and optionally a
    per-span metrics.jsonl record), and
  * a ``jax.profiler.TraceAnnotation`` scope with the same (nested) path,

so a phase in the host timeline and the same phase in a device trace
captured via ``--profile-dir`` carry identical names and can be lined up.
This replaces the ad-hoc StepTimer call sites in trainer.py/benchmark.py
(utils/timers.py keeps StepTimer for the sync/timing primitives the
benchmark harness builds on; the span API is the instrumentation layer).

Spans nest: ``span("train")`` containing ``span("io")`` accumulates under
the path ``"train/io"``. Nesting is tracked per-thread, so the prefetch
worker's spans cannot interleave into the consumer thread's path.
"""

from __future__ import annotations

import functools
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

import jax

from gtopkssgd_tpu.utils.timers import TimingStats


class Tracer:
    def __init__(
        self,
        stats: Optional[TimingStats] = None,
        metrics=None,
        enabled: bool = True,
        record_each: bool = False,
        sink=None,
    ):
        """``metrics`` is a utils.metrics.MetricsLogger (or anything with
        ``.log(kind, **fields)``). ``record_each=True`` writes one jsonl
        record per span close — verbose; the default accumulates into
        ``stats`` and ships means via ``flush()``. ``sink`` is an
        optional callable ``(path, t0_perf_counter, dur_seconds)``
        invoked on every span close — the timeline recorder's hook
        (obs.timeline.TimelineRecorder.span_sink matches it)."""
        self.stats = stats or TimingStats()
        self.metrics = metrics
        self.enabled = enabled
        self.record_each = record_each
        self.sink = sink
        self._local = threading.local()

    def _stack(self):
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @property
    def current_path(self) -> str:
        return "/".join(self._stack())

    @contextmanager
    def span(self, name: str, *, sync: bool = False, value=None, **attrs):
        """Time a scope under ``name`` (nested under any open spans).

        ``sync=True`` blocks on JAX's async queue before stopping the
        clock (``value`` fences just that output) — same semantics as the
        StepTimer this API replaces; leave False for host-only phases
        like data loading, and for dispatch phases where the async queue
        must NOT be drained (the whole point of overlap)."""
        if not self.enabled:
            yield
            return
        stack = self._stack()
        stack.append(name)
        path = "/".join(stack)
        ann = jax.profiler.TraceAnnotation(path)
        t0 = time.perf_counter()
        ann.__enter__()
        try:
            yield
        finally:
            try:
                if sync:
                    if value is not None:
                        jax.block_until_ready(value)
                    else:
                        jax.effects_barrier()
            finally:
                ann.__exit__(None, None, None)
                dur = time.perf_counter() - t0
                stack.pop()
                self.stats.add(path, dur)
                if self.sink is not None:
                    self.sink(path, t0, dur)
                if self.record_each and self.metrics is not None:
                    self.metrics.log(
                        "span", name=name, path=path, dur_s=dur, **attrs
                    )

    def annotate(self, name: Optional[str] = None):
        """Decorator form (the jax.profiler.annotate_function idiom):
        every call of the wrapped function runs inside a span."""

        def deco(fn):
            label = name or fn.__name__

            @functools.wraps(fn)
            def wrapped(*args, **kwargs):
                with self.span(label):
                    return fn(*args, **kwargs)

            return wrapped

        return deco

    def flush(self, step: Optional[int] = None) -> Dict[str, float]:
        """Ship accumulated per-path mean seconds as ONE 'spans' record
        and reset, so each logging window reports its own means (the
        reference logged its timer dicts every N iterations the same
        way). Returns the summary that was logged."""
        summary = self.stats.summary()
        if summary and self.metrics is not None:
            rec = {} if step is None else {"step": step}
            rec.update({path: round(sec, 6) for path, sec in summary.items()})
            self.metrics.log("spans", **rec)
        self.stats.reset()
        return summary
