"""Comm-model ledger: measured T_comm / wire bytes vs the alpha-beta model.

The paper's scaling argument (arXiv:1901.04359 §3, re-parameterized in
``benchmarks/scaling_model.py``) predicts per-step communication time
from mode, worker count, gradient size and link constants. PRs 1–3 made
the MEASURED side observable — per-rank ``attr`` records carry the
profiler-derived T_comm split, ``obs`` counter records carry the achieved
wire_bytes — but nothing ever reconciled the two. This module does the
join: for every rank (and step, where attribution is per-step) it emits a
predicted-vs-measured ratio row, so the report can say "comm is 1.8x the
alpha-beta model on ranks 3–4" instead of leaving both numbers in
separate files.

Reading a ratio:
  ~1       the model explains the wire — imbalance hunting should look
           at compute/input, not the collective
  >>1      measured comm far above model: congestion, a straggling host
           serializing the tree rounds, or link constants that flatter
           the hardware (re-run benchmarks/dcn_probe.py and feed its
           alpha_beta_fit back in)
  <1       model too pessimistic (overlap the model ignores, or compute
           classified as comm leaked out of attribution)

Model constants come from, in priority order: explicit arguments, a
``dcn_probe`` artifact's ``alpha_beta_fit`` (``load_alpha_beta``), and
the scaling model's documented defaults. The scaling model itself is
loaded from ``benchmarks/`` by path (benchmarks is not a package); when
the benchmarks tree is absent (installed-package use) a self-contained
pure alpha-beta fallback keeps the ledger functional.
"""

from __future__ import annotations

import glob
import importlib.util
import json
import math
import os
import re
from typing import Any, Dict, List, Mapping, Optional, Sequence

# scaling_model.py main() defaults — mirrored here for the fallback path
# and for callers that pass no constants at all.
DEFAULT_ICI_GBPS = 1600.0
DEFAULT_DCN_GBPS = 25.0


def _load_scaling_model():
    """Import benchmarks/scaling_model.py by path (repo root is 3 hops
    up from this file); None when the benchmarks tree is absent."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(repo, "benchmarks", "scaling_model.py")
    if not os.path.exists(path):
        return None
    spec = importlib.util.spec_from_file_location("_obs_scaling_model",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(mod)
    except Exception:
        return None
    return mod


def _tree_rounds_fallback(p: int) -> int:
    if p <= 1:
        return 0
    m = 1 << (p.bit_length() - 1)
    return (m.bit_length() - 1) + (0 if m == p else 2)


def _codec_set_bytes(codec: str, k: int, n: int) -> int:
    """On-wire bytes of one encoded k-of-n sparse set under `codec` —
    the one shared definition (parallel.codec.WireCodec.wire_set_bytes)
    when the package is importable, else the fp32 identity (8 bytes per
    element), so a bare-ledger install still reconciles uncompressed
    runs."""
    try:
        from gtopkssgd_tpu.parallel.codec import get_codec
        return get_codec(codec).wire_set_bytes(k, n)
    except Exception:
        return 8 * k


def _balanced_cap(k: int, p: int, n: int) -> int:
    """Per-destination capacity of the balanced schedule — the shared
    definition (parallel.collectives.balanced_cap) when importable, else
    the same closed form, so a bare-ledger install still models it."""
    try:
        from gtopkssgd_tpu.parallel.collectives import balanced_cap
        return balanced_cap(k, p, n)
    except Exception:
        return max(1, min(-(-3 * k // (2 * p)), k, -(-n // p)))


def wire_mode_for(mode: str, schedule: Optional[str] = None,
                  bucketing: Optional[str] = None) -> str:
    """Comm-model key for (semantic mode, wire schedule, bucketing): the
    layerwise mode shares the flat tree's wire, and the 'balanced'
    schedule maps the gtopk family onto the Ok-Topk model branch.
    None/'auto'/'tree' keep the mode's historical model — exactly
    sparse_allreduce's plan dispatch, so the ledger always prices the
    schedule that actually ran.

    ``bucketing`` (parallel.bucketing.buckets_key grammar) changes the
    merge MULTIPLICITY, not the per-merge model, so the key stays the
    same base wire mode; pricing callers pass the bucket (n_b, k_b)
    pairs to ``predict_comm_ms(buckets=...)`` and the model sums B
    independent merges of that key. The parameter exists here so every
    plan/ledger call site names the full wire decision in one place."""
    wm = "gtopk" if mode == "gtopk_layerwise" else mode
    if schedule == "balanced" and wm in ("gtopk", "gtopk_hier"):
        return "gtopk_balanced"
    return wm


def predict_comm_ms(mode: str, p: int, *, n: int, k: int,
                    alpha_ms: float = 0.0,
                    beta_gbps: float = DEFAULT_DCN_GBPS,
                    ici_gbps: float = DEFAULT_ICI_GBPS,
                    ici_size: int = 1,
                    codec: str = "fp32",
                    buckets: Optional[Sequence[Sequence[int]]] = None
                    ) -> float:
    """Predicted comm_ms via scaling_model.predict when benchmarks/ is
    importable, else a pure alpha-beta tree model (rounds x alpha +
    bytes/beta on the slow link) — the degenerate ici_size=1 case of the
    full model, which is exactly the multi-process CPU/DCN topology the
    ledger's tests and typical --multihost runs live on. ``codec`` sets
    the per-round sparse payload size (parallel.codec wire bytes).

    ``buckets`` — ((n_b, k_b), ...) from a BucketPlan — prices the
    bucketed layerwise wire: B independent merges, each over its
    bucket-local index space, summed. The per-merge model is unchanged,
    which is exactly what the bucketed optimizer path executes."""
    if buckets:
        return sum(
            predict_comm_ms(mode, p, n=int(n_b), k=int(k_b),
                            alpha_ms=alpha_ms, beta_gbps=beta_gbps,
                            ici_gbps=ici_gbps, ici_size=ici_size,
                            codec=codec)
            for n_b, k_b in buckets)
    sm = _load_scaling_model()
    if sm is not None and hasattr(sm, "predict"):
        return sm.predict(mode, p, n=n, k=k, ici_gbps=ici_gbps,
                          dcn_gbps=beta_gbps, ici_size=ici_size,
                          dcn_alpha_ms=alpha_ms, codec=codec)
    beta_Bps = beta_gbps * 1e9 / 8
    wire_mode = "gtopk" if mode == "gtopk_layerwise" else mode
    if wire_mode == "dense":
        bytes_per_dev = 2.0 * (p - 1) / p * 4 * n if p > 1 else 0.0
        return (bytes_per_dev / beta_Bps * 1e3
                + 2 * (p - 1) * alpha_ms)
    rounds = _tree_rounds_fallback(p)
    set_bytes = _codec_set_bytes(codec, k, n)
    if wire_mode == "gtopk":
        return rounds * (set_bytes / beta_Bps * 1e3 + alpha_ms)
    if wire_mode == "gtopk_balanced":
        # Ok-Topk schedule: p-1 scatter rounds + p-1 gather hops, each
        # moving one cap-of-n encoded set over the slow link.
        cap_bytes = _codec_set_bytes(codec, _balanced_cap(k, p, n), n)
        msgs = 2 * (p - 1)
        return msgs * (cap_bytes / beta_Bps * 1e3 + alpha_ms)
    if wire_mode == "allgather":
        return (set_bytes * (p - 1) / beta_Bps * 1e3
                + (p - 1) * alpha_ms)
    if wire_mode == "gtopk_hier":
        return rounds * (set_bytes / beta_Bps * 1e3 + alpha_ms)
    raise ValueError(mode)


# Fit-artifact filename grammar: the probe writes dcn_probe_{P}proc.json,
# the in-run calibrator (obs/calib.py) writes calib_fit_{P}proc.json with
# the same alpha_beta_fit payload. One regex recovers (family, P) for the
# numeric precedence sort below.
_FIT_ARTIFACT_RE = re.compile(r"^(dcn_probe|calib_fit)_(\d+)proc\.json$")


def _fit_artifact_key(path: str):
    """Precedence sort key (higher wins): proc count NUMERICALLY first —
    the docstring's "largest proc count present" contract, which a plain
    lexicographic basename sort breaks the moment two counts share no
    digit width (it ranked 8proc over 16proc) — then, at equal P, a
    calib_fit over a dcn_probe: the calibrator measured THIS workload's
    wire in-situ, the probe measured synthetic pings."""
    m = _FIT_ARTIFACT_RE.match(os.path.basename(path))
    if m is None:
        return (-1, 0, os.path.basename(path))
    return (int(m.group(2)), 1 if m.group(1) == "calib_fit" else 0,
            os.path.basename(path))


def _parse_fit_artifact(path: str) -> Optional[Dict[str, Any]]:
    """{alpha_ms, beta_gbps, source[, axes]} from one fit artifact, or
    None when unreadable/unusable. The optional ``axes`` section maps
    axis name -> per-axis fit ({"ici": {...}, "dcn": {...}} today,
    arbitrary mesh-axis names later); only axes with numeric alpha_ms
    and beta_gbps > 0 survive parsing."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    fit = doc.get("alpha_beta_fit") or {}
    alpha, beta = fit.get("alpha_ms"), fit.get("beta_gbps")
    if not (isinstance(alpha, (int, float))
            and isinstance(beta, (int, float)) and beta > 0):
        return None
    out: Dict[str, Any] = {"alpha_ms": float(alpha),
                           "beta_gbps": float(beta),
                           "source": os.path.basename(path)}
    # Theil-Sen residual noise floor (obs/calib.py) — the forecast
    # plane's uncertainty-band source. Probe-era artifacts predate it;
    # absent means "no measured band", never 0-invented.
    if isinstance(fit.get("resid_ms"), (int, float)) \
            and fit["resid_ms"] >= 0:
        out["resid_ms"] = float(fit["resid_ms"])
    axes = doc.get("axes")
    if isinstance(axes, dict):
        clean: Dict[str, Dict[str, float]] = {}
        for name, ax in axes.items():
            if (isinstance(ax, dict)
                    and isinstance(ax.get("alpha_ms"), (int, float))
                    and isinstance(ax.get("beta_gbps"), (int, float))
                    and ax["beta_gbps"] > 0):
                clean[str(name)] = {"alpha_ms": float(ax["alpha_ms"]),
                                    "beta_gbps": float(ax["beta_gbps"])}
                if isinstance(ax.get("resid_ms"), (int, float)) \
                        and ax["resid_ms"] >= 0:
                    clean[str(name)]["resid_ms"] = float(ax["resid_ms"])
        if clean:
            out["axes"] = clean
    return out


def load_alpha_beta(search_dir: Optional[str] = None,
                    nprocs: Optional[int] = None
                    ) -> Optional[Dict[str, Any]]:
    """The fitted {alpha_ms, beta_gbps} from a fit artifact —
    ``dcn_probe_{n}proc.json`` (benchmarks/dcn_probe.py) or
    ``calib_fit_{n}proc.json`` (obs/calib.py, the in-run calibrator) —
    or None. ``nprocs`` restricts to that exact proc count; otherwise
    the largest proc count present wins (closest to a real fleet), with
    proc counts compared numerically. At equal proc count an artifact
    carrying a per-axis ``axes`` section outranks an axis-blind one
    (two measured hops price a hierarchical plan better than one
    blended fit — same spirit as the calib-over-probe rule), then a
    calib_fit outranks a dcn_probe (the calibrator measured the actual
    workload's collectives; the probe measured synthetic pings). The
    returned dict carries the ``axes`` section through when present.
    Default search dir: benchmarks/results/."""
    if search_dir is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        search_dir = os.path.join(repo, "benchmarks", "results")
    if nprocs is not None:
        paths = [os.path.join(search_dir, f"calib_fit_{nprocs}proc.json"),
                 os.path.join(search_dir, f"dcn_probe_{nprocs}proc.json")]
    else:
        paths = sorted(
            glob.glob(os.path.join(search_dir, "dcn_probe_*proc.json"))
            + glob.glob(os.path.join(search_dir, "calib_fit_*proc.json")),
            key=_fit_artifact_key, reverse=True)
    best_key, best = None, None
    for path in paths:
        parsed = _parse_fit_artifact(path)
        if parsed is None:
            continue
        p_key, calib_key, name = _fit_artifact_key(path)
        key = (p_key, 1 if "axes" in parsed else 0, calib_key, name)
        if best_key is None or key > best_key:
            best_key, best = key, parsed
    return best


def _manifest_params(manifest: Optional[Mapping[str, Any]]
                     ) -> Optional[Dict[str, Any]]:
    """(mode, p, n, k) from a run-manifest record; None when the header
    lacks what the model needs."""
    if not manifest:
        return None
    mode = manifest.get("compression")
    p = manifest.get("nworkers")
    n = manifest.get("num_params")
    if not mode or not isinstance(p, int) or not isinstance(n, int):
        return None
    rho = manifest.get("density")
    k = (max(1, math.ceil(rho * n))
         if isinstance(rho, (int, float)) and rho > 0 else n)
    if mode == "dense":
        k = n
    codec = manifest.get("wire_codec")
    # The planner stamps the resolved wire schedule into the manifest
    # (comm_plan_schedule; comm_plan is the plan NAME, kept for humans).
    # Pre-planner runs have neither -> None -> historical model.
    schedule = manifest.get("comm_plan_schedule")
    # Bucketed layerwise runs additionally stamp the chosen partition
    # (BucketPlan.to_manifest): per-bucket element counts and wire ks.
    # Unbucketed runs (and every pre-bucketing run) have neither ->
    # buckets=None -> the single-merge model.
    sizes, ks = manifest.get("bucket_sizes"), manifest.get("bucket_ks")
    buckets = None
    if (isinstance(sizes, (list, tuple)) and isinstance(ks, (list, tuple))
            and sizes and len(sizes) == len(ks)):
        buckets = tuple(
            (int(n_b), int(k_b)) for n_b, k_b in zip(sizes, ks))
    return {"mode": str(mode), "p": p, "n": n, "k": k,
            "codec": str(codec) if codec else "fp32",
            "schedule": str(schedule) if schedule else None,
            "bucketing": str(manifest.get("buckets") or "concat"),
            "buckets": buckets}


def ledger_rows(records: Sequence[Mapping[str, Any]],
                manifest: Optional[Mapping[str, Any]] = None,
                alpha_ms: Optional[float] = None,
                beta_gbps: Optional[float] = None,
                ici_gbps: float = DEFAULT_ICI_GBPS,
                ici_size: Optional[int] = None,
                probe_dir: Optional[str] = None) -> List[dict]:
    """The join: one ratio row per measured T_comm observation.

    ``records`` is a merged (or single-shard) record stream; the
    manifest (explicit, or found in-stream) supplies the model inputs;
    ``attr`` records supply measured per-rank T_comm (t_comm_us) and the
    ``obs`` counter records supply measured wire_bytes per step. Fitted
    alpha/beta default to the newest dcn_probe artifact when present.
    Returns [] rather than guessing when the manifest can't parameterize
    the model.
    """
    if manifest is None:
        for rec in records:
            if rec.get("kind") == "manifest":
                manifest = rec
                break
    params = _manifest_params(manifest)
    if params is None:
        return []

    fit_source = "defaults"
    if alpha_ms is None or beta_gbps is None:
        fit = load_alpha_beta(search_dir=probe_dir)
        if fit is not None:
            alpha_ms = fit["alpha_ms"] if alpha_ms is None else alpha_ms
            beta_gbps = (fit["beta_gbps"] if beta_gbps is None
                         else beta_gbps)
            fit_source = fit["source"]
    alpha_ms = 0.0 if alpha_ms is None else float(alpha_ms)
    beta_gbps = (DEFAULT_DCN_GBPS if beta_gbps is None
                 else float(beta_gbps))

    if ici_size is None:
        # Cross-process hops are the slow link; devices per process is
        # the natural ICI-domain size. process_count is in the manifest
        # since PR 2; absent (or single-process) means every hop is
        # "DCN" for the fallback topology, which is the conservative
        # read for a ledger about the slow link.
        pc = manifest.get("process_count") if manifest else None
        if isinstance(pc, int) and pc > 1 and params["p"] % pc == 0:
            ici_size = params["p"] // pc
        else:
            ici_size = 1

    wm = wire_mode_for(params["mode"], params.get("schedule"),
                       bucketing=params.get("bucketing"))
    buckets = params.get("buckets")
    predicted_ms = predict_comm_ms(
        wm, params["p"], n=params["n"], k=params["k"],
        alpha_ms=alpha_ms, beta_gbps=beta_gbps, ici_gbps=ici_gbps,
        ici_size=ici_size, codec=params["codec"], buckets=buckets)

    base = {
        "mode": params["mode"], "p": params["p"],
        "n": params["n"], "k": params["k"], "codec": params["codec"],
        "schedule": params.get("schedule"),
        "bucketing": params.get("bucketing", "concat"),
        "n_buckets": len(buckets) if buckets else None,
        "alpha_ms": round(alpha_ms, 6), "beta_gbps": round(beta_gbps, 6),
        "ici_size": ici_size, "fit_source": fit_source,
        "predicted_comm_ms": round(predicted_ms, 6),
    }
    rows: List[dict] = []
    for rec in records:
        kind = rec.get("kind")
        rank = rec.get("rank", 0)
        if kind == "attr":
            t_comm_us = rec.get("t_comm_us")
            if not isinstance(t_comm_us, (int, float)):
                continue
            measured_ms = float(t_comm_us) / 1e3
            n_steps = rec.get("n_steps")
            if isinstance(n_steps, (int, float)) and n_steps > 0:
                measured_ms /= float(n_steps)
            rows.append({
                **base, "source": "attr", "rank": rank,
                "step": rec.get("step"),
                "measured_comm_ms": round(measured_ms, 6),
                "ratio": (round(measured_ms / predicted_ms, 6)
                          if predicted_ms > 0 else None),
            })
        elif kind == "obs":
            wire = rec.get("wire_bytes")
            if not isinstance(wire, (int, float)) or wire <= 0:
                continue
            # Bytes-side sanity row: achieved wire bytes vs the model's
            # per-device volume (codec set bytes per sparse round — 8k
            # under the fp32 identity; dense ring 2(p-1)/p x 4n). No
            # timing — the ratio checks volume accounting, the attr rows
            # check time. Bucketed runs sum the per-merge volume over
            # the stamped (n_b, k_b) pairs — the same B merges the
            # optimizer issues and the telemetry counter models.
            p = params["p"]

            def _sparse_pred_bytes(k, nn):
                set_bytes = _codec_set_bytes(params["codec"], k, nn)
                if wm == "gtopk_balanced":
                    # comm_bytes_per_step's balanced formula verbatim:
                    # p-1 scatter rounds + a p-slice allgather, one
                    # encoded cap-of-n set each.
                    return max(1, 2 * p - 1) * _codec_set_bytes(
                        params["codec"], _balanced_cap(k, p, nn), nn)
                if wm in ("gtopk", "gtopk_hier"):
                    return _tree_rounds_fallback(
                        p if wm == "gtopk"
                        else max(1, p // ici_size)) * set_bytes
                if wm == "allgather":
                    return set_bytes * (p - 1)
                return 0.0

            if wm == "dense":
                nn = params["n"]
                pred_bytes = 2.0 * (p - 1) / p * 4 * nn if p > 1 else 0.0
            elif buckets:
                pred_bytes = sum(
                    _sparse_pred_bytes(k_b, n_b) for n_b, k_b in buckets)
            else:
                pred_bytes = _sparse_pred_bytes(params["k"], params["n"])
            rows.append({
                **base, "source": "wire_bytes", "rank": rank,
                "step": rec.get("step"),
                "measured_wire_bytes": float(wire),
                "predicted_wire_bytes": round(pred_bytes, 1),
                "ratio": (round(float(wire) / pred_bytes, 6)
                          if pred_bytes > 0 else None),
            })
    return rows


def summarize_ledger(rows: Sequence[Mapping[str, Any]]
                     ) -> Dict[str, Dict[str, Any]]:
    """{source: {count, mean_ratio, min_ratio, max_ratio, worst_ranks}}
    — the report's one-glance view; worst_ranks are the ranks whose mean
    ratio sits highest (the "ranks 3–4" in the module docstring)."""
    by_source: Dict[str, List[Mapping[str, Any]]] = {}
    for row in rows:
        if isinstance(row.get("ratio"), (int, float)):
            by_source.setdefault(str(row.get("source")), []).append(row)
    out: Dict[str, Dict[str, Any]] = {}
    for source, rws in by_source.items():
        ratios = [float(r["ratio"]) for r in rws]
        by_rank: Dict[Any, List[float]] = {}
        for r in rws:
            by_rank.setdefault(r.get("rank", 0), []).append(
                float(r["ratio"]))
        rank_means = {rk: sum(v) / len(v) for rk, v in by_rank.items()}
        worst = sorted(rank_means, key=rank_means.get, reverse=True)[:2]
        out[source] = {
            "count": len(ratios),
            "mean_ratio": round(sum(ratios) / len(ratios), 4),
            "min_ratio": round(min(ratios), 4),
            "max_ratio": round(max(ratios), 4),
            "worst_ranks": {str(rk): round(rank_means[rk], 4)
                            for rk in worst},
        }
    return out
