"""Metrics report CLI: aggregate metrics.jsonl runs, compare two of them,
or gate one against a committed baseline.

    python -m gtopkssgd_tpu.obs.report <run>            # summarize one run
    python -m gtopkssgd_tpu.obs.report <runA> <runB>    # side-by-side diff
    python -m gtopkssgd_tpu.obs.report <run> --json out.json
    python -m gtopkssgd_tpu.obs.report gate <run> --baseline base.json
    python -m gtopkssgd_tpu.obs.report attr <run|trace> # T_compute/T_select/
                                                        # T_comm decomposition
    python -m gtopkssgd_tpu.obs.report events <run>     # anomaly events by rule
    python -m gtopkssgd_tpu.obs.report recovery <run>   # injected faults +
                                                        # recovery actions
    python -m gtopkssgd_tpu.obs.report timeline <run>   # rebuild timeline.json
    python -m gtopkssgd_tpu.obs.report fleet <run>...   # cross-rank merge +
                                                        # straggler attribution
    python -m gtopkssgd_tpu.obs.report critpath <run>...
                                                        # global per-step
                                                        # critical path: which
                                                        # (rank, stage) bounds
                                                        # each step, wait split
    python -m gtopkssgd_tpu.obs.report goodput <run>...
                                                        # goodput/badput
                                                        # decomposition per
                                                        # rank + fleet roll-up
                                                        # (--advise, --compare)
    python -m gtopkssgd_tpu.obs.report watch <run>...   # live tail-follow
    python -m gtopkssgd_tpu.obs.report ledger <run>...  # comm model vs measured
    python -m gtopkssgd_tpu.obs.report linkmap <run>... # per-(axis, peer)
                                                        # network weather map +
                                                        # per-axis calib fits
    python -m gtopkssgd_tpu.obs.report forecast <run>...
                                                        # hindcast error + per-P
                                                        # scale-out forecast
                                                        # grid with uncertainty
                                                        # bands, crossover P
    python -m gtopkssgd_tpu.obs.report history <dir>    # registry trend table
                                                        # (obs/registry.py)
    python -m gtopkssgd_tpu.obs.report regress <run> --registry <dir>
                                                        # current run vs registry
                                                        # baseline, gate exits
    python -m gtopkssgd_tpu.obs.report compile <run>    # per-shape AOT compile
                                                        # log + recompile watch
    python -m gtopkssgd_tpu.obs.report mem <run>        # live-memory footprint,
                                                        # compile log, leak/
                                                        # headroom summary

A <run> is a directory containing metrics.jsonl (what --out-dir produces)
or a path to any .jsonl file of MetricsLogger records. Multi-process runs
shard per rank (``metrics.rank{r}.jsonl``, utils/metrics.py): a directory
holding shards but no metrics.jsonl loads as the concatenation of all its
shards, so every subcommand — including the two-run compare, whose means
over concatenated shards ARE the fleet-merged means — works on fleet
dirs unchanged. Records group by their ``kind`` ("train", "eval", "obs",
"spans", "epoch", ...); every numeric field gets count/mean/min/max/last.
When the run has a manifest header it is printed first, and "layers"
records additionally get a per-layer breakdown table (one row per layer,
mean of each counters.LAYER_FIELDS column). The two-run mode prints mean
vs. mean with a signed delta per field — the bench-regression triage view
(was r05 slower because comm grew, or because achieved density drifted?).
Kinds not registered in utils.metrics.KINDS are flagged with a note
(records from a future/modified writer, or hand-edited files).

``gate`` is the regression gate: the baseline JSON carries a ``checks``
list ({kind, field, stat, expect, rtol, atol, optional layer}) and an
optional ``manifest`` dict of exact-match provenance keys; a check passes
iff |actual - expect| <= atol + rtol*|expect|. Exit 0 = all pass, 1 = any
regression (or a checked field missing from the run), 2 = usage error.
``--write`` re-stamps the baseline's expectations from the run under test
(the regeneration path after an intentional behavior change).

Malformed lines are counted and skipped, never fatal: a run killed by the
stall watchdog (or the kernel) may leave a torn final line, and the whole
point of the report is reading evidence out of exactly such runs.
"""

from __future__ import annotations

import argparse
import json
import os
import time as _time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from gtopkssgd_tpu.utils.metrics import KINDS, shard_rank

# Bookkeeping fields that are not measurements; excluded from aggregation.
_META_FIELDS = {"kind", "time", "rank"}


def resolve_path(run: str) -> str:
    """<run dir> -> its metrics.jsonl; a file path passes through. When
    the dir has only rank shards, rank 0's shard is the representative
    single path (use resolve_paths for the whole fleet)."""
    if os.path.isdir(run):
        single = os.path.join(run, "metrics.jsonl")
        if os.path.exists(single):
            return single
        shards = _shard_paths(run)
        if shards:
            return shards[0]
        return single
    return run


def _shard_paths(run_dir: str) -> List[str]:
    """metrics.rank{r}.jsonl shards in a dir, sorted by rank."""
    found = []
    for name in os.listdir(run_dir):
        r = shard_rank(name)
        if r is not None:
            found.append((r, os.path.join(run_dir, name)))
    return [path for _, path in sorted(found)]


def resolve_paths(run: str) -> List[str]:
    """Every record file a run target names: [metrics.jsonl] for classic
    runs, all rank shards (rank order) for sharded dirs, the file itself
    for file paths."""
    if os.path.isdir(run):
        single = os.path.join(run, "metrics.jsonl")
        if os.path.exists(single):
            return [single]
        shards = _shard_paths(run)
        return shards if shards else [single]
    return [run]


def _parse_lines(lines: Iterable[str]) -> Tuple[List[dict], int]:
    records, bad = [], 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            bad += 1
            continue
        if isinstance(rec, dict):
            records.append(rec)
        else:
            bad += 1
    return records, bad


def load_records(run: str) -> Tuple[List[dict], int]:
    """Parse a run's records — concatenating rank shards (rank order)
    when the target is a sharded dir, so aggregate means over a fleet
    dir ARE the fleet-merged means. Returns (records, n_malformed)."""
    records, bad = [], 0
    for path in resolve_paths(run):
        with open(path) as fh:
            recs, b = _parse_lines(fh)
        records.extend(recs)
        bad += b
    return records, bad


def unregistered_kinds(records: Iterable[dict]) -> List[str]:
    """Kinds present in a record stream but missing from the writer's
    registry (utils.metrics.KINDS) — a hand-edited file or a
    version-skewed writer; flagged, never fatal."""
    return sorted({str(rec.get("kind")) for rec in records
                   if rec.get("kind") not in KINDS})


def summarize(records: Iterable[dict]) -> Dict[str, Dict[str, dict]]:
    """{kind: {field: {count, mean, min, max, last}}} over numeric fields."""
    acc: Dict[str, Dict[str, List[float]]] = {}
    for rec in records:
        kind = str(rec.get("kind", "?"))
        if kind == "manifest":
            continue  # provenance header, not a measurement stream
        fields = acc.setdefault(kind, {})
        for key, val in rec.items():
            if key in _META_FIELDS:
                continue
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            fields.setdefault(key, []).append(float(val))
    out: Dict[str, Dict[str, dict]] = {}
    for kind, fields in acc.items():
        out[kind] = {}
        for key, vals in fields.items():
            out[kind][key] = {
                "count": len(vals),
                "mean": sum(vals) / len(vals),
                "min": min(vals),
                "max": max(vals),
                "last": vals[-1],
            }
    return out


def extract_manifest(records: Iterable[dict]) -> Optional[dict]:
    """The run's manifest record (kind "manifest"), or None. First wins:
    the trainer writes it before any measurement record."""
    for rec in records:
        if rec.get("kind") == "manifest":
            return rec
    return None


def summarize_layers(records: Iterable[dict]) -> Dict[str, Dict[str, dict]]:
    """{layer: {field: {count, mean, min, max, last}}} over the numeric
    fields of kind=="layers" records (the per-layer telemetry stream)."""
    by_layer: Dict[str, List[dict]] = {}
    for rec in records:
        if rec.get("kind") != "layers":
            continue
        by_layer.setdefault(str(rec.get("layer", "?")), []).append(rec)
    return {
        layer: summarize(recs).get("layers", {})
        for layer, recs in by_layer.items()
    }


def format_manifest(man: dict) -> str:
    rows = [
        [key, json.dumps(val) if isinstance(val, dict) else str(val)]
        for key, val in man.items()
        if key not in _META_FIELDS
    ]
    return "[manifest]\n" + _table(rows, ["key", "value"])


# Per-layer table column order; "layer" (the row key) and "step" are
# implicit. Mirrors counters.LAYER_FIELDS without importing jax here.
_LAYER_COLUMNS = ("density", "tau", "m_k", "residual_age", "residual_norm",
                  "grad_norm_pre", "grad_norm_post")


def format_layers(by_layer: Dict[str, Dict[str, dict]]) -> str:
    """One row per layer, mean of each per-layer counter over the run."""
    cols = [c for c in _LAYER_COLUMNS
            if any(c in fields for fields in by_layer.values())]
    rows = []
    for layer in sorted(by_layer):
        fields = by_layer[layer]
        rows.append([layer] + [
            _fmt(fields[c]["mean"]) if c in fields else "-" for c in cols
        ])
    n = max((max(s["count"] for s in f.values()) if f else 0)
            for f in by_layer.values())
    return (f"[layers] ({len(by_layer)} layers x {n} obs steps; "
            "mean per layer)\n"
            + _table(rows, ["layer"] + [f"mean({c})" for c in cols]))


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "nan"
    a = abs(v)
    if (a != 0 and a < 1e-3) or a >= 1e7:
        return f"{v:.4g}"
    if a >= 100 or v == int(v):
        return f"{v:.6g}"
    return f"{v:.4f}"


def _table(rows: List[Sequence[str]], header: Sequence[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows)
        for i in range(len(header))
    ]
    lines = []
    for r in [header, ["-" * w for w in widths]] + rows:
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


def format_summary(name: str, summary: Dict[str, Dict[str, dict]],
                   kinds: Optional[Sequence[str]] = None) -> str:
    chunks = [f"run: {name}"]
    for kind in sorted(summary):
        if kinds and kind not in kinds:
            continue
        fields = summary[kind]
        if not fields:
            continue
        n = max(s["count"] for s in fields.values())
        chunks.append(f"\n[{kind}] ({n} records)")
        rows = [
            [key, str(s["count"]), _fmt(s["mean"]), _fmt(s["min"]),
             _fmt(s["max"]), _fmt(s["last"])]
            for key, s in sorted(fields.items())
        ]
        chunks.append(
            _table(rows, ["field", "count", "mean", "min", "max", "last"]))
    return "\n".join(chunks)


def compare(a: Dict[str, Dict[str, dict]],
            b: Dict[str, Dict[str, dict]]) -> Dict[str, Dict[str, dict]]:
    """Per-kind/field mean-vs-mean diff for every field both runs have."""
    out: Dict[str, Dict[str, dict]] = {}
    for kind in sorted(set(a) & set(b)):
        fields = sorted(set(a[kind]) & set(b[kind]))
        if not fields:
            continue
        out[kind] = {}
        for key in fields:
            ma, mb = a[kind][key]["mean"], b[kind][key]["mean"]
            delta = mb - ma
            # A zero baseline has no meaningful relative change: record
            # None (rendered "—"), never a `+nan%` column; the absolute
            # delta still prints.
            pct = (delta / abs(ma) * 100.0) if ma else None
            out[kind][key] = {"mean_a": ma, "mean_b": mb,
                              "delta": delta, "delta_pct": pct}
    return out


def format_compare(name_a: str, name_b: str,
                   diff: Dict[str, Dict[str, dict]],
                   kinds: Optional[Sequence[str]] = None) -> str:
    chunks = [f"compare: A={name_a}  B={name_b}"]
    for kind in sorted(diff):
        if kinds and kind not in kinds:
            continue
        rows = []
        for key, d in sorted(diff[kind].items()):
            pct = d["delta_pct"]
            rows.append([
                key, _fmt(d["mean_a"]), _fmt(d["mean_b"]), _fmt(d["delta"]),
                ("—" if pct is None or pct != pct else f"{pct:+.1f}%"),
            ])
        if rows:
            chunks.append(f"\n[{kind}]")
            chunks.append(_table(
                rows, ["field", "mean_A", "mean_B", "delta", "delta%"]))
    return "\n".join(chunks)


def _lookup_stat(summary: Dict[str, Dict[str, dict]],
                 layers: Dict[str, Dict[str, dict]],
                 check: dict) -> Optional[float]:
    """Resolve one baseline check against a run's aggregates; None when
    the kind/layer/field/stat is absent (reported as a failure — a
    silently vanished counter IS a regression)."""
    stat = str(check.get("stat", "mean"))
    if check.get("layer") is not None:
        fields = layers.get(str(check["layer"]), {})
    else:
        fields = summary.get(str(check.get("kind", "obs")), {})
    entry = fields.get(str(check["field"]))
    if entry is None or stat not in entry:
        return None
    return float(entry[stat])


def _check_id(check: dict) -> str:
    where = (f"layers[{check['layer']}]" if check.get("layer") is not None
             else str(check.get("kind", "obs")))
    return f"{where}.{check['field']}.{check.get('stat', 'mean')}"


def run_gate(run: str, baseline_path: str,
             write: Optional[str] = None) -> int:
    """Diff a run against a committed baseline JSON; 0 pass / 1 fail."""
    try:
        with open(baseline_path) as fh:
            baseline = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"cannot read baseline {baseline_path}: {e}")
        return 2
    checks = baseline.get("checks")
    if not isinstance(checks, list) or not checks:
        print(f"baseline {baseline_path} has no 'checks' list")
        return 2
    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: {run}: skipped {bad} malformed line(s)")
    summary = summarize(records)
    layers = summarize_layers(records)
    manifest = extract_manifest(records) or {}

    failures = 0
    rows = []
    for key, expect in sorted((baseline.get("manifest") or {}).items()):
        actual = manifest.get(key)
        ok = actual == expect
        failures += not ok
        rows.append([f"manifest.{key}", json.dumps(expect),
                     json.dumps(actual), "-", "OK" if ok else "FAIL"])
    for check in checks:
        expect = float(check["expect"])
        rtol = float(check.get("rtol", 0.0))
        atol = float(check.get("atol", 0.0))
        tol = atol + rtol * abs(expect)
        actual = _lookup_stat(summary, layers, check)
        if actual is None:
            failures += 1
            rows.append([_check_id(check), _fmt(expect), "missing",
                         _fmt(tol), "FAIL"])
            continue
        ok = abs(actual - expect) <= tol
        failures += not ok
        rows.append([_check_id(check), _fmt(expect), _fmt(actual),
                     _fmt(tol), "OK" if ok else "FAIL"])
    print(f"gate: run={run}  baseline={baseline_path}")
    print(_table(rows, ["check", "expect", "actual", "tol", "status"]))
    print(f"gate: {len(rows) - failures}/{len(rows)} checks passed")

    if write:
        # Regeneration path: keep each check's spec (tolerances, stat,
        # addressing) but re-stamp 'expect' from the run under test, and
        # refresh the pinned manifest keys. Review the diff like code.
        new_checks = []
        for check in checks:
            actual = _lookup_stat(summary, layers, check)
            out = dict(check)
            if actual is not None:
                out["expect"] = actual
            new_checks.append(out)
        new_base = dict(baseline)
        new_base["checks"] = new_checks
        if baseline.get("manifest"):
            new_base["manifest"] = {
                key: manifest.get(key) for key in baseline["manifest"]
            }
        with open(write, "w") as fh:
            json.dump(new_base, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {write}")
    return 1 if failures else 0


def _is_run(target: str) -> bool:
    """Does the target look like a metrics run (vs. a profiler trace)?"""
    if os.path.isdir(target):
        return (os.path.exists(os.path.join(target, "metrics.jsonl"))
                or bool(_shard_paths(target)))
    return target.endswith(".jsonl")


def run_attr(target: str, mode: Optional[str] = None,
             json_out: Optional[str] = None) -> int:
    """``attr`` subcommand: print the paper's T_compute/T_select/T_comm
    table. The target is either a run (metrics.jsonl carrying logged
    "attr" records — the gate smoke writes one) or a profiler trace
    dir/file, which is parsed and attributed on the spot."""
    from gtopkssgd_tpu.obs import trace_attr

    if _is_run(target):
        try:
            records, bad = load_records(target)
        except OSError as e:
            print(f"cannot read {target}: {e}")
            return 2
        recs = [{k: v for k, v in r.items() if k not in _META_FIELDS}
                for r in records if r.get("kind") == "attr"]
        if not recs:
            print(f"{target}: no attr records (pass a trace dir, or log "
                  "one via obs.trace_attr.attribute)")
            return 1
    else:
        try:
            recs = [trace_attr.attribute(target, mode=mode)]
        except (FileNotFoundError, OSError, ValueError) as e:
            print(f"cannot attribute {target}: {e}")
            return 2
    for rec in recs:
        print(trace_attr.format_attr(rec))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump(recs if len(recs) > 1 else recs[0], fh, indent=1,
                      sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def summarize_events(records: Iterable[dict]) -> Dict[str, dict]:
    """{rule: {severity, count, first_step, last_step, last_value,
    threshold, last_message}} over kind=="event" records."""
    by_rule: Dict[str, dict] = {}
    for rec in records:
        if rec.get("kind") != "event":
            continue
        rule = str(rec.get("rule", "?"))
        r = by_rule.setdefault(rule, {
            "severity": rec.get("severity"), "count": 0,
            "first_step": None, "last_step": None, "last_value": None,
            "threshold": rec.get("threshold"), "last_message": None,
        })
        r["count"] += 1
        r["severity"] = rec.get("severity", r["severity"])
        step = rec.get("step")
        if isinstance(step, (int, float)):
            r["first_step"] = (step if r["first_step"] is None
                               else min(r["first_step"], step))
            r["last_step"] = (step if r["last_step"] is None
                              else max(r["last_step"], step))
        r["last_value"] = rec.get("value", r["last_value"])
        r["threshold"] = rec.get("threshold", r["threshold"])
        r["last_message"] = rec.get("message", r["last_message"])
    return by_rule


def format_events(name: str, by_rule: Dict[str, dict]) -> str:
    if not by_rule:
        return f"events: {name}: none recorded"
    rows = []
    for rule in sorted(by_rule):
        r = by_rule[rule]
        rows.append([
            rule, str(r["severity"]), str(r["count"]),
            "-" if r["first_step"] is None else _fmt(r["first_step"]),
            "-" if r["last_step"] is None else _fmt(r["last_step"]),
            "-" if r["last_value"] is None else _fmt(r["last_value"]),
            "-" if r["threshold"] is None else _fmt(r["threshold"]),
        ])
    out = [f"events: {name}",
           _table(rows, ["rule", "severity", "count", "first_step",
                         "last_step", "last_value", "threshold"])]
    for rule in sorted(by_rule):
        msg = by_rule[rule]["last_message"]
        if msg:
            out.append(f"  {rule}: {msg}")
    return "\n".join(out)


def run_events(run: str, json_out: Optional[str] = None) -> int:
    """``events`` subcommand: summarize a run's anomaly stream per rule."""
    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: {run}: skipped {bad} malformed line(s)")
    by_rule = summarize_events(records)
    name = os.path.basename(os.path.normpath(run)) or run
    print(format_events(name, by_rule))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump(by_rule, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def summarize_recovery(records: Iterable[dict]) -> dict:
    """Resilience view over one run's records: injected faults (kind
    "inject"), recovery actions (kind "recovery"), claimed vs unclaimed
    anomaly events, and the end-of-run summary record's verdict."""
    out = {
        "injected": {},        # fault kind -> {count, first_step, last_step}
        "actions": {},         # action -> {count, rules, first_step, last_step}
        "events_claimed": 0,
        "events_unclaimed": 0,
        "final_status": None,
        "n_recoveries": None,
        "final_step": None,
    }
    for rec in records:
        kind = rec.get("kind")
        step = rec.get("step")
        if kind == "inject":
            f = out["injected"].setdefault(str(rec.get("fault", "?")), {
                "count": 0, "first_step": None, "last_step": None})
            f["count"] += 1
            if isinstance(step, (int, float)):
                f["first_step"] = (step if f["first_step"] is None
                                   else min(f["first_step"], step))
                f["last_step"] = (step if f["last_step"] is None
                                  else max(f["last_step"], step))
        elif kind == "recovery":
            action = str(rec.get("action", "?"))
            if action == "summary":
                out["final_status"] = rec.get("final_status")
                out["n_recoveries"] = rec.get("n_recoveries")
                out["final_step"] = step
                continue
            a = out["actions"].setdefault(action, {
                "count": 0, "rules": {}, "first_step": None,
                "last_step": None})
            a["count"] += 1
            rule = rec.get("rule")
            if rule is not None:
                a["rules"][str(rule)] = a["rules"].get(str(rule), 0) + 1
            if isinstance(step, (int, float)):
                a["first_step"] = (step if a["first_step"] is None
                                   else min(a["first_step"], step))
                a["last_step"] = (step if a["last_step"] is None
                                  else max(a["last_step"], step))
        elif kind == "event":
            if rec.get("claimed"):
                out["events_claimed"] += 1
            else:
                out["events_unclaimed"] += 1
    return out


def format_recovery(name: str, summary: dict) -> str:
    chunks = [f"recovery: {name}"]
    injected = summary["injected"]
    if injected:
        rows = [[fault, str(f["count"]),
                 "-" if f["first_step"] is None else _fmt(f["first_step"]),
                 "-" if f["last_step"] is None else _fmt(f["last_step"])]
                for fault, f in sorted(injected.items())]
        chunks.append(f"\n[inject] ({sum(f['count'] for f in injected.values())} firings)")
        chunks.append(_table(rows, ["fault", "count", "first_step",
                                    "last_step"]))
    actions = summary["actions"]
    if actions:
        rows = []
        for action, a in sorted(actions.items()):
            rules = "  ".join(f"{rule}={n}"
                              for rule, n in sorted(a["rules"].items()))
            rows.append([
                action, str(a["count"]),
                "-" if a["first_step"] is None else _fmt(a["first_step"]),
                "-" if a["last_step"] is None else _fmt(a["last_step"]),
                rules or "-"])
        chunks.append(f"\n[recovery] ({sum(a['count'] for a in actions.values())} actions)")
        chunks.append(_table(rows, ["action", "count", "first_step",
                                    "last_step", "rules"]))
    if not injected and not actions:
        chunks.append("no injected faults or recovery actions recorded")
    claimed, unclaimed = (summary["events_claimed"],
                          summary["events_unclaimed"])
    if claimed or unclaimed:
        chunks.append(f"\nanomaly events: {claimed} claimed by recovery, "
                      f"{unclaimed} unclaimed")
    if summary["final_status"] is not None:
        chunks.append(
            f"final: status={summary['final_status']} "
            f"n_recoveries={summary['n_recoveries']} "
            + ("" if summary["final_step"] is None
               else f"step={_fmt(summary['final_step'])}"))
    return "\n".join(chunks)


def run_recovery(run: str, json_out: Optional[str] = None) -> int:
    """``recovery`` subcommand: the resilience story of one run —
    injected faults, recovery actions by kind, claimed/unclaimed events,
    and the end-of-run verdict."""
    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: {run}: skipped {bad} malformed line(s)")
    summary = summarize_recovery(records)
    name = os.path.basename(os.path.normpath(run)) or run
    print(format_recovery(name, summary))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump(summary, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def run_timeline(run: str, out: Optional[str] = None) -> int:
    """``timeline`` subcommand: rebuild a chrome-trace timeline from a
    run's metrics.jsonl (markers + counter tracks at recorded wall-clock
    times), validate it, and write it next to the run."""
    from gtopkssgd_tpu.obs.timeline import (
        timeline_from_records,
        validate_timeline,
    )

    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: {run}: skipped {bad} malformed line(s)")
    name = os.path.basename(os.path.normpath(run)) or run
    doc = timeline_from_records(records, label=name)
    problems = validate_timeline(doc)
    if out is None:
        base = run if os.path.isdir(run) else os.path.dirname(run) or "."
        out = os.path.join(base, "timeline.json")
    with open(out, "w") as fh:
        json.dump(doc, fh)
        fh.write("\n")
    n = sum(1 for e in doc["traceEvents"] if e.get("ph") != "M")
    print(f"timeline: {name}: {n} events -> {out}"
          + (" (open in chrome://tracing or ui.perfetto.dev)"))
    for p in problems:
        print(f"invalid: {p}")
    return 1 if problems else 0


def format_fleet(merged: dict, kinds: Optional[Sequence[str]] = None,
                 max_rows: int = 0) -> str:
    """The fleet view: per-(src, step, field) stat rows, then straggler
    attribution, then fired events. ``max_rows`` > 0 truncates the stat
    table (watch mode); 0 prints everything."""
    chunks = [f"fleet: ranks={merged['ranks']} "
              f"shards={len(merged['shards'])}"]
    man = merged.get("manifest") or {}
    if man:
        bits = [f"{key}={man[key]}" for key in
                ("compression", "nworkers", "process_count", "config_hash")
                if man.get(key) is not None]
        if bits:
            chunks.append("  " + "  ".join(bits))
    rows = merged["rows"]
    if kinds:
        rows = [r for r in rows if r["src"] in kinds]
    table = []
    shown = rows if max_rows <= 0 else rows[-max_rows:]
    for r in shown:
        worst = (max(r["skew"], key=lambda rk: abs(r["skew"][rk]))
                 if r["skew"] else "-")
        table.append([r["src"], _fmt(r["step"]), r["field"],
                      str(r["n_ranks"]), _fmt(r["min"]), _fmt(r["median"]),
                      _fmt(r["max"]), _fmt(r["std"]), _fmt(r["skew_max"]),
                      str(worst)])
    if table:
        chunks.append(f"\n[fleet] ({len(rows)} merged rows"
                      + (f", last {len(shown)}" if len(shown) < len(rows)
                         else "") + ")")
        chunks.append(_table(table, ["src", "step", "field", "n_ranks",
                                     "min", "median", "max", "std",
                                     "skew_max", "worst"]))
    stragglers = merged.get("stragglers") or []
    if stragglers:
        st = [[_fmt(s["step"]), f"r{s['slowest_rank']}",
               _fmt(s["behind_median_s"]), _fmt(s["lag_s"]),
               _fmt(s["ewma_lag_s"]),
               "persistent" if s["persistent"] else "transient",
               str(s.get("stage") or "-")]
              for s in stragglers]
        chunks.append(f"\n[straggler] (src={stragglers[0]['src']}; lag = "
                      "arrival behind first rank at each step's record; "
                      "stage = the slowest rank's local critical stage)")
        chunks.append(_table(st, ["step", "slowest", "behind_median_s",
                                  "lag_s", "ewma_lag_s", "class",
                                  "stage"]))
        persistent = [s for s in stragglers if s["persistent"]]
        if persistent:
            worst = persistent[-1]
            chunks.append(
                f"persistent straggler: rank {worst['slowest_rank']} "
                f"(EWMA lag {_fmt(worst['ewma_lag_s'])}s over "
                f"{len(persistent)} flagged steps)")
    crit = merged.get("critpath") or []
    if crit:
        counts: Dict[str, int] = {}
        for r in crit:
            st = r.get("crit_stage")
            if st:
                counts[st] = counts.get(st, 0) + 1
        modal = (max(sorted(counts), key=lambda s: counts[s])
                 if counts else None)
        mean_frac = sum(float(r.get("crit_frac", 0.0))
                        for r in crit) / len(crit)
        chunks.append(f"\n[critpath] {len(crit)} joined step(s)  "
                      f"modal critical stage: {modal}  "
                      f"mean crit_frac={mean_frac:.4f}  "
                      "(report critpath for the full chain)")
    events = merged.get("events") or []
    if events:
        by_rule: Dict[str, int] = {}
        for ev in events:
            by_rule[ev["rule"]] = by_rule.get(ev["rule"], 0) + 1
        chunks.append("\n[events] "
                      + "  ".join(f"{rule}={n}"
                                  for rule, n in sorted(by_rule.items())))
    return "\n".join(chunks)


def run_fleet(targets: Sequence[str], kinds: Optional[Sequence[str]],
              json_out: Optional[str] = None,
              allow_mismatch: bool = False) -> int:
    """``fleet`` subcommand: merge rank shards (one or many dirs/files),
    print per-step cross-rank stats + straggler attribution."""
    from gtopkssgd_tpu.obs import fleet

    try:
        merged = fleet.merge(list(targets),
                             kinds=tuple(kinds) if kinds
                             else fleet.DEFAULT_KINDS,
                             allow_mismatch=allow_mismatch)
    except (OSError, ValueError) as e:
        print(f"cannot merge {list(targets)}: {e}")
        return 2
    if merged["n_malformed"]:
        print(f"note: skipped {merged['n_malformed']} malformed line(s)")
    print(format_fleet(merged, kinds=None))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump(merged, fh, indent=1, sort_keys=True, default=str)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def run_critpath(targets: Sequence[str], json_out: Optional[str] = None,
                 allow_mismatch: bool = False,
                 halt_on: Optional[str] = None) -> int:
    """``critpath`` subcommand: join per-rank ``critpath`` stage-interval
    records (obs/critpath.py) across shards into the global per-step
    critical path — which (rank, stage) chain bounds each step, how much
    of T_comm was wire vs skew-wait, and where each rank's blocked time
    went. ``halt_on`` arms the ``critpath_shift`` rule exactly like the
    trainer's --obs-halt-on: a modal-stage shift exits HALT_EXIT_CODE
    after its event row is printed."""
    from gtopkssgd_tpu.obs import critpath as _critpath
    from gtopkssgd_tpu.obs import fleet
    from gtopkssgd_tpu.obs.events import (
        HALT_EXIT_CODE,
        AnomalyHalt,
        AnomalyMonitor,
    )

    try:
        shards = fleet.resolve_targets(list(targets))
        records_by_rank, bad = fleet.load_shards(shards)
        fleet.validate_shards(records_by_rank,
                              allow_mismatch=allow_mismatch)
    except (OSError, ValueError) as e:
        print(f"cannot merge {list(targets)}: {e}")
        return 2
    if bad:
        print(f"note: skipped {bad} malformed line(s)")
    monitor = AnomalyMonitor(halt_on=halt_on)
    try:
        rows, budgets = fleet.critpath_rows(records_by_rank,
                                            monitor=monitor)
        halted = None
    except AnomalyHalt as e:
        halted = e.event
        rows, budgets = [], {}
    if halted is not None:
        print(f"critpath: HALT on {halted['rule']} at step "
              f"{halted.get('step')}: {halted.get('message')}")
        return HALT_EXIT_CODE
    if not rows:
        print("critpath: no critpath records (run with --obs-critpath, "
              "or the shards predate the stage-interval plane)")
        return 1
    print(f"critpath: ranks={sorted(records_by_rank)} "
          f"steps={len(rows)}")
    print(_critpath.format_critpath(rows, budgets))
    events = list(monitor.events)
    if events:
        by_rule: Dict[str, int] = {}
        for ev in events:
            by_rule[ev["rule"]] = by_rule.get(ev["rule"], 0) + 1
        print("\n[events] " + "  ".join(
            f"{rule}={n}" for rule, n in sorted(by_rule.items())))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump({"rows": rows, "budgets": budgets,
                       "events": events}, fh, indent=1, sort_keys=True,
                      default=str)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def run_goodput(targets: Sequence[str], json_out: Optional[str] = None,
                allow_mismatch: bool = False, advise: bool = False,
                compare: Optional[str] = None) -> int:
    """``goodput`` subcommand: per-rank goodput/badput decomposition
    (obs/goodput.py) — category table, per-rank goodput bars, the
    whole-fleet wall-weighted roll-up; ``--compare OTHER`` diffs this
    run's fleet decomposition against another run's (the chaos-vs-clean
    view); ``--advise`` prints the eviction hint (which rank's badput
    drags furthest below the fleet median, and what evicting it would
    recover)."""
    from gtopkssgd_tpu.obs import fleet
    from gtopkssgd_tpu.obs import goodput as _goodput

    try:
        shards = fleet.resolve_targets(list(targets))
        records_by_rank, bad = fleet.load_shards(shards)
        fleet.validate_shards(records_by_rank,
                              allow_mismatch=allow_mismatch)
    except (OSError, ValueError) as e:
        print(f"cannot merge {list(targets)}: {e}")
        return 2
    if bad:
        print(f"note: skipped {bad} malformed line(s)")
    decomp = _goodput.fold_shards(records_by_rank)
    if not decomp:
        print("goodput: no goodput records and nothing to synthesize "
              "from (run with --obs-goodput, the default)")
        return 1
    fleet_rec = _goodput.fleet_decomposition(decomp)
    cmp_decomp = None
    if compare:
        try:
            cshards = fleet.resolve_targets([compare])
            crecs, cbad = fleet.load_shards(cshards)
            if cbad:
                print(f"note: {compare}: skipped {cbad} malformed "
                      "line(s)")
            cmp_decomp = _goodput.fold_shards(crecs) or None
        except (OSError, ValueError) as e:
            print(f"cannot read compare run {compare}: {e}")
            return 2
    hint = _goodput.advise(decomp) if advise else None
    print(f"goodput: ranks={sorted(decomp)}")
    print(_goodput.format_goodput(decomp, fleet=fleet_rec,
                                  compare=cmp_decomp, hint=hint))
    if advise and hint is None:
        print("advise: no outlier — every rank within margin of the "
              "fleet median goodput_frac")
    if json_out:
        with open(json_out, "w") as fh:
            json.dump({"by_rank": decomp, "fleet": fleet_rec,
                       "compare": cmp_decomp, "advise": hint},
                      fh, indent=1, sort_keys=True, default=str)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def run_watch(targets: Sequence[str], interval: float = 2.0,
              iterations: Optional[int] = None, out=None) -> int:
    """``watch`` subcommand: tail-follow one or many shards, printing a
    refreshing per-rank summary block per poll. Incremental — each poll
    reads only bytes appended since the last (line-buffered writers make
    whole records visible mid-run). ``iterations`` bounds the loop for
    tests/scripting; the default runs until interrupted."""
    import sys
    out = out or sys.stdout

    # rank -> [path, offset, n_records, n_bad, last_rec_by_kind,
    #          last_two_record_times]
    state: Dict[int, list] = {}

    def discover():
        for target in targets:
            if os.path.isdir(target):
                for path in _shard_paths(target) or [
                        os.path.join(target, "metrics.jsonl")]:
                    r = shard_rank(path)
                    state.setdefault(r if r is not None else 0,
                                     [path, 0, 0, 0, {}, []])
            else:
                r = shard_rank(target)
                state.setdefault(r if r is not None else 0,
                                 [target, 0, 0, 0, {}, []])

    n_polls = 0
    try:
        while True:
            discover()  # shards appear as ranks start up
            for rank in sorted(state):
                st = state[rank]
                path, offset = st[0], st[1]
                try:
                    with open(path) as fh:
                        fh.seek(offset)
                        chunk = fh.read()
                        st[1] = fh.tell()
                except OSError:
                    continue
                recs, bad = _parse_lines(chunk.splitlines())
                st[2] += len(recs)
                st[3] += bad
                for rec in recs:
                    st[4][str(rec.get("kind"))] = rec
                    ts = rec.get("time")
                    if isinstance(ts, (int, float)):
                        st[5].append(float(ts))
                        del st[5][:-2]
            stamp = _time.strftime("%H:%M:%S")
            print(f"watch @ {stamp}  ({len(state)} rank(s))", file=out)
            # Live straggler view: each rank's latest per-step record
            # arrival vs the cross-rank median — the same
            # behind_median_s the fleet straggler rows report, computed
            # over whatever each shard has flushed so far.
            arrivals: Dict[int, float] = {}
            for rank in sorted(state):
                last = state[rank][4]
                for kind in ("train", "obs", "eval"):
                    rec = last.get(kind)
                    if rec is not None and isinstance(
                            rec.get("time"), (int, float)):
                        arrivals[rank] = float(rec["time"])
                        break
            med_arrival = None
            if len(arrivals) >= 2:
                vals = sorted(arrivals.values())
                mid = len(vals) // 2
                med_arrival = (vals[mid] if len(vals) % 2
                               else 0.5 * (vals[mid - 1] + vals[mid]))
            for rank in sorted(state):
                path, _, n, bad, last = state[rank][:5]
                times = state[rank][5]
                latest = None
                for kind in ("train", "obs", "eval"):
                    if kind in last:
                        latest = last[kind]
                        break
                bits = [f"rank {rank}", f"records={n}"]
                if latest is not None:
                    if latest.get("step") is not None:
                        bits.append(f"step={_fmt(latest['step'])}")
                    for key in ("loss", "achieved_density", "wire_bytes"):
                        if isinstance(latest.get(key), (int, float)):
                            bits.append(f"{key}={_fmt(latest[key])}")
                if med_arrival is not None and rank in arrivals:
                    bits.append(
                        "behind_median_s="
                        f"{_fmt(arrivals[rank] - med_arrival)}")
                cp = last.get("critpath")
                if cp is not None and cp.get("crit_stage"):
                    # this rank's local critical stage (latest critpath
                    # record) — why it is slow, not just that it is.
                    bits.append(f"crit_stage={cp['crit_stage']}")
                gp = last.get("goodput")
                if gp is not None and isinstance(
                        gp.get("goodput_frac"), (int, float)):
                    # latest cumulative ledger record (--obs-goodput):
                    # this rank's productive share of wall so far.
                    bits.append(f"goodput_frac={_fmt(gp['goodput_frac'])}")
                mem = last.get("mem")
                if mem is not None:
                    # space-plane gauges (--obs-mem): same fields the
                    # OpenMetrics exporter serves as gtopk_mem_*.
                    for key in ("live_bytes", "bytes_in_use",
                                "recompile_count"):
                        if isinstance(mem.get(key), (int, float)):
                            bits.append(f"{key}={_fmt(mem[key])}")
                lm = last.get("linkmap")
                if lm is not None and lm.get("worst_link"):
                    # the rank's slowest peer hop (latest weather-map
                    # record) and how far it sits above its link median.
                    x = lm.get("worst_over_median_x")
                    bits.append(
                        f"slowest_peer={lm['worst_link']}"
                        + (f"({_fmt(x)}x)"
                           if isinstance(x, (int, float)) else ""))
                if times:
                    # freshness: seconds since the shard's newest record;
                    # STALE once the gap exceeds 3x the rank's own log
                    # cadence (last inter-record interval) — a wedged or
                    # dead rank keeps serving its last gauges otherwise.
                    age = max(0.0, _time.time() - times[-1])
                    bits.append(f"age_s={_fmt(age)}")
                    cadence = (times[-1] - times[-2]
                               if len(times) >= 2 else None)
                    if cadence and cadence > 0 and age > 3 * cadence:
                        bits.append("STALE")
                ev = last.get("event")
                if ev is not None:
                    bits.append(f"last_event={ev.get('rule')}")
                if bad:
                    bits.append(f"malformed={bad}")
                if n == 0:
                    bits.append("(no records yet)")
                print("  " + "  ".join(bits), file=out)
            out.flush()
            n_polls += 1
            if iterations is not None and n_polls >= iterations:
                return 0
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def run_ledger(targets: Sequence[str], json_out: Optional[str] = None,
               alpha_ms: Optional[float] = None,
               beta_gbps: Optional[float] = None,
               probe_dir: Optional[str] = None) -> int:
    """``ledger`` subcommand: predicted-vs-measured comm rows over a
    run's (or fleet's) records."""
    from gtopkssgd_tpu.obs import ledger

    records = []
    for target in targets:
        try:
            recs, bad = load_records(target)
        except OSError as e:
            print(f"cannot read {target}: {e}")
            return 2
        if bad:
            print(f"note: {target}: skipped {bad} malformed line(s)")
        records.extend(recs)
    rows = ledger.ledger_rows(records, alpha_ms=alpha_ms,
                              beta_gbps=beta_gbps, probe_dir=probe_dir)
    if not rows:
        print("ledger: no joinable records (need a manifest with "
              "compression/nworkers/num_params plus attr or obs "
              "wire_bytes records)")
        return 1
    base = rows[0]
    bucketing = base.get("bucketing", "concat")
    bucket_note = ("" if bucketing in (None, "concat") else
                   f" bucketing={bucketing} "
                   f"n_buckets={base.get('n_buckets')}")
    print(f"ledger: mode={base['mode']} p={base['p']} n={base['n']} "
          f"k={base['k']} codec={base.get('codec', 'fp32')}"
          f"{bucket_note}  alpha_ms={base['alpha_ms']} "
          f"beta_gbps={base['beta_gbps']} ici_size={base['ici_size']} "
          f"(fit: {base['fit_source']})")
    prov = _fit_provenance_line(records)
    if prov:
        print(prov)
    print(f"predicted comm: {_fmt(base['predicted_comm_ms'])} ms/step")
    # Codec-bytes audit: modeled vs measured wire bytes per rank (the
    # wire_bytes rows carry both sides of the join).
    wire_rows = [r for r in rows if r.get("source") == "wire_bytes"
                 and isinstance(r.get("predicted_wire_bytes"),
                                (int, float))]
    if wire_rows:
        by_rank = {}
        for r in wire_rows:
            by_rank.setdefault(r.get("rank", 0), []).append(r)
        parts = []
        for rk in sorted(by_rank):
            rws = by_rank[rk]
            meas = sum(float(r["measured_wire_bytes"])
                       for r in rws) / len(rws)
            pred = float(rws[0]["predicted_wire_bytes"])
            parts.append(f"r{rk}: {_fmt(pred)}B model / "
                         f"{_fmt(meas)}B measured")
        print(f"codec bytes ({base.get('codec', 'fp32')}): "
              + "  ".join(parts))
    summary = ledger.summarize_ledger(rows)
    table = []
    for source in sorted(summary):
        s = summary[source]
        worst = "  ".join(f"r{rk}={v}" for rk, v in
                          s["worst_ranks"].items())
        table.append([source, str(s["count"]), _fmt(s["mean_ratio"]),
                      _fmt(s["min_ratio"]), _fmt(s["max_ratio"]), worst])
    print(_table(table, ["source", "rows", "mean_ratio", "min_ratio",
                         "max_ratio", "worst_ranks"]))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump({"rows": rows, "summary": summary}, fh, indent=1,
                      sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def run_linkmap(targets: Sequence[str],
                json_out: Optional[str] = None) -> int:
    """``linkmap`` subcommand: join one or many runs' per-rank
    "linkmap" records into the fleet network weather map — per-(axis,
    peer) EWMA latency/bandwidth with endpoint averaging, the worst
    link vs the fleet median, and the per-axis calib fit lines when the
    stream carries dotted per-axis calib fields."""
    from gtopkssgd_tpu.obs import linkmap as _linkmap

    records = []
    for target in targets:
        try:
            recs, bad = load_records(target)
        except OSError as e:
            print(f"cannot read {target}: {e}")
            return 2
        if bad:
            print(f"note: {target}: skipped {bad} malformed line(s)")
        records.extend(recs)
    summary = _linkmap.summarize_linkmap(records)
    print(_linkmap.format_linkmap(summary))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump(summary, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0 if summary["rows"] else 1


def run_forecast(targets: Sequence[str],
                 json_out: Optional[str] = None,
                 search_dir: Optional[str] = None,
                 forecast_targets: Optional[str] = None) -> int:
    """``forecast`` subcommand: the scale-out forecast view
    (obs/forecast.py) — hindcast error against the run's own measured
    step time, the per-P recommendation grid with resid-derived
    uncertainty columns, and the tree->balanced crossover P. A run that
    logged live ``forecast`` records is reported from its last one;
    otherwise the view is rebuilt offline from the stream's manifest +
    critpath + calib + linkmap records (and the fit-artifact lookup
    under ``--probe-dir``)."""
    from gtopkssgd_tpu.obs import forecast as _forecast

    records = []
    for target in targets:
        try:
            recs, bad = load_records(target)
        except OSError as e:
            print(f"cannot read {target}: {e}")
            return 2
        if bad:
            print(f"note: {target}: skipped {bad} malformed line(s)")
        records.extend(recs)
    ts = None
    if forecast_targets:
        try:
            ts = tuple(int(t) for t in forecast_targets.split(",")
                       if t.strip())
        except ValueError:
            print(f"--targets must be comma-separated worker counts, "
                  f"got {forecast_targets!r}")
            return 2
    summary = _forecast.summarize_forecast(records, search_dir=search_dir,
                                           targets=ts)
    print(_forecast.format_forecast(summary))
    if json_out:
        payload = {k: v for k, v in summary.items()}
        if isinstance(payload.get("recs"), dict):
            payload["recs"] = {str(p): row for p, row
                               in payload["recs"].items()}
        with open(json_out, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0 if summary.get("rows") else 1


def _fit_provenance_line(records: Iterable[dict]) -> Optional[str]:
    """The manifest's stamped comm-model provenance ("which comm model
    priced this plan"), or None for runs that predate the stamp. Printed
    by the plan and ledger headers — including when the source is a
    calib_fit artifact from a previous calibrated run."""
    man = extract_manifest(records)
    if man is None or man.get("comm_fit_source") is None:
        return None
    return (f"manifest fit: {man['comm_fit_source']} "
            f"(alpha_ms={man.get('comm_fit_alpha_ms')} "
            f"beta_gbps={man.get('comm_fit_beta_gbps')})")


def run_history(registry_dir: str, config_hash: Optional[str] = None,
                json_out: Optional[str] = None) -> int:
    """``history`` subcommand: the registry's cross-run trend table
    (obs/registry.py runs.jsonl), offline — no live run needed."""
    from gtopkssgd_tpu.obs import registry as _registry

    entries, bad = _registry.load_registry(registry_dir)
    if bad:
        print(f"note: skipped {bad} malformed registry line(s)")
    if not entries:
        print(f"history: no registry entries under {registry_dir} "
              f"(runs append via --registry {registry_dir})")
        return 1
    rows = _registry.history_rows(entries, config_hash=config_hash)
    if not rows:
        print(f"history: no entries match config_hash={config_hash}")
        return 1
    print(f"history: {len(rows)} run(s)"
          + (f" with config_hash={config_hash}" if config_hash else
             f" across {len({e.get('config_hash') for e in entries})} "
             "config(s)"))
    print(_table(rows, _registry.HISTORY_HEADER))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump({"entries": entries}, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def run_regress(run: str, registry_dir: str,
                allow_mismatch: bool = False,
                json_out: Optional[str] = None) -> int:
    """``regress`` subcommand: summarize the run under test from its
    shards, diff it against the most recent same-config registry entry
    under REGRESS_CHECKS tolerances. Exit contract matches ``gate``:
    0 within tolerance, 1 regression, 2 usage (unreadable run, empty
    registry, or no comparable baseline without --allow-mismatch)."""
    from gtopkssgd_tpu.obs import registry as _registry

    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: skipped {bad} malformed line(s)")
    entry = _registry.run_summary(records)
    if entry is None:
        print("regress: run has no manifest record — nothing to key the "
              "baseline lookup on")
        return 2
    entries, rbad = _registry.load_registry(registry_dir)
    if rbad:
        print(f"note: skipped {rbad} malformed registry line(s)")
    if not entries:
        print(f"regress: no registry entries under {registry_dir}")
        return 2
    baseline = _registry.pick_baseline(entry, entries,
                                       allow_mismatch=allow_mismatch)
    if baseline is None:
        print(f"regress: no registry entry matches config_hash="
              f"{entry.get('config_hash')} (rerun with --allow-mismatch "
              "to compare against the newest entry of any config)")
        return 2
    if baseline.get("config_hash") != entry.get("config_hash"):
        print(f"note: baseline config_hash "
              f"{baseline.get('config_hash')} != run's "
              f"{entry.get('config_hash')} (--allow-mismatch)")
    rows, failures = _registry.regress(entry, baseline)
    print(f"regress: {run} vs registry entry "
          f"(config={baseline.get('config_hash', '?')}, "
          f"git={baseline.get('git_sha', '?')})")
    print(_table(rows, _registry.REGRESS_HEADER))
    checked = sum(1 for r in rows if r[-1] != "new")
    print(f"regress: {checked - failures}/{checked} checks passed")
    if json_out:
        with open(json_out, "w") as fh:
            json.dump({"current": entry, "baseline": baseline,
                       "failures": failures}, fh, indent=1,
                      sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 1 if failures else 0


def build_gate_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "gtopkssgd_tpu.obs.report gate",
        description="Diff a run against a committed baseline JSON; exit "
                    "nonzero on regression.",
    )
    p.add_argument("run", help="an --out-dir or a metrics.jsonl path")
    p.add_argument("--baseline", required=True,
                   help="baseline JSON with a 'checks' list and optional "
                        "'manifest' exact-match dict")
    p.add_argument("--write", default=None,
                   help="write a regenerated baseline (same check specs, "
                        "expectations re-stamped from this run) here")
    return p


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "gtopkssgd_tpu.obs.report",
        description="Aggregate metrics.jsonl runs; compare two for "
                    "regression triage.",
    )
    p.add_argument("runs", nargs="+",
                   help="1 or 2 runs: an --out-dir (containing "
                        "metrics.jsonl) or a .jsonl path")
    p.add_argument("--kinds", default=None,
                   help="comma-separated record kinds to report "
                        "(default: all present)")
    p.add_argument("--json", dest="json_out", default=None,
                   help="also write the aggregate (or diff) as JSON here")
    return p


def run_plan(run: str, json_out: Optional[str] = None) -> int:
    """``plan`` subcommand: the comm-planner decision record — which
    wire plan the run chose, why (every candidate's modeled comm_ms and
    per-step wire bytes), and the alpha-beta inputs the scores used."""
    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: skipped {bad} malformed line(s)")
    decisions = [r for r in records if r.get("kind") == "plan"
                 and isinstance(r.get("candidates"), list)]
    bucket_recs = [r for r in records if r.get("kind") == "bucket"
                   and isinstance(r.get("rows"), list)]
    if not decisions and not bucket_recs:
        print("plan: no planner decision record (dense or single-device "
              "runs have no sparse wire to plan; pre-planner runs "
              "predate the record)")
        return 1
    prov = _fit_provenance_line(records)
    if prov:
        print(prov)
    # The space plane next to the time plane: when the run carried
    # --obs-mem, say what the chosen plan costs in HBM.
    comp = summarize_compile(records)
    if comp["peak_hbm_bytes"] is not None:
        print(f"memory: peak-HBM estimate {_fmt(comp['peak_hbm_bytes'])} "
              f"bytes over {len(comp['shapes'])} dispatch shape(s) "
              "(obs.memwatch compile records)")
    for rec in decisions:
        pin = rec.get("pin", "auto")
        how = f"pinned via --comm-plan {pin}" if pin != "auto" else (
            "auto-selected (cheapest modeled comm_ms; historical "
            "schedule wins ties)")
        print(f"plan: {rec.get('plan')} (schedule={rec.get('schedule')}"
              f", wire_mode={rec.get('wire_mode')}, pipeline="
              f"{rec.get('pipeline', 'serial')}) for mode="
              f"{rec.get('mode')} — {how}")
        print(f"inputs: p={rec.get('p')} n={rec.get('n')} k={rec.get('k')}"
              f" codec={rec.get('codec')} ici_size={rec.get('ici_size')}"
              f"  alpha_ms={rec.get('alpha_ms')} "
              f"beta_gbps={rec.get('beta_gbps')} "
              f"ici_gbps={rec.get('ici_gbps')} "
              f"(fit: {rec.get('fit_source')})")
        rows = []
        # Span columns appear once candidates carry them (post-pipeline
        # planner); older records print the comm-only table unchanged.
        have_spans = any(c.get("span_serial_ms") is not None
                         for c in rec["candidates"])
        for c in rec["candidates"]:
            mark = "*" if c.get("name") == rec.get("plan") else ""
            row = [f"{c.get('name')}{mark}",
                   str(c.get('schedule')),
                   _fmt(c.get('comm_ms')),
                   _fmt(c.get('wire_bytes'))]
            if have_spans:
                row += [_fmt(c.get('span_serial_ms')),
                        _fmt(c.get('span_overlap_ms'))]
            rows.append(row)
        header = ["candidate", "schedule", "comm_ms", "wire_bytes/step"]
        if have_spans:
            header += ["span_serial_ms", "span_overlap_ms"]
        print(_table(rows, header))
    # Bucket plan (parallel.bucketing): boundaries the run actually used
    # plus the modeled ms of the degenerate partitions, so the reader
    # sees where the chosen B sits on the alpha-beta curve.
    for rec in bucket_recs:
        pipe = rec.get("pipeline")
        print(f"buckets: {rec.get('buckets')} -> B={rec.get('n_buckets')}"
              f" over L={rec.get('n_leaves')} leaves  "
              + (f"pipeline={pipe}  " if pipe else "")
              + f"(alpha_ms={rec.get('alpha_ms')} "
              f"beta_gbps={rec.get('beta_gbps')})")
        print(f"modeled comm ms: B=1 {_fmt(rec.get('modeled_ms_b1'))}  "
              f"chosen {_fmt(rec.get('modeled_ms'))}  "
              f"B=L {_fmt(rec.get('modeled_ms_leaf'))}")
        # stage_ms rows exist on post-pipeline records: the per-bucket
        # DP objective (merge under serial, max(select, merge) under
        # overlap) next to the raw merge cost.
        have_stage = any(r.get("stage_ms") is not None
                         for r in rec["rows"])
        rows = []
        for r in rec["rows"]:
            row = [str(r.get("bucket")), str(r.get("leaves")),
                   str(r.get("n_leaves")), str(r.get("elems")),
                   str(r.get("k")), _fmt(r.get("wire_bytes")),
                   _fmt(r.get("modeled_ms"))]
            if have_stage:
                row += [_fmt(r.get("select_ms")), _fmt(r.get("stage_ms"))]
            rows.append(row)
        header = ["bucket", "leaves", "n_leaves", "elems", "k",
                  "wire_bytes", "modeled_ms"]
        if have_stage:
            header += ["select_ms", "stage_ms"]
        print(_table(rows, header))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump({"decisions": decisions, "buckets": bucket_recs},
                      fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


# Fields a compile-shape row carries into summaries and the JSON dump.
_COMPILE_ROW_FIELDS = (
    "step", "shape_index", "shape_key", "flops", "bytes_accessed",
    "temp_bytes", "argument_bytes", "output_bytes", "generated_code_bytes",
    "peak_hbm_bytes", "lower_s", "compile_s")

# The memory-plane anomaly rules (obs/events.py) the mem report calls out.
_MEM_RULES = ("recompile_storm", "device_mem_leak", "hbm_headroom")


def summarize_compile(records: Iterable[dict]) -> dict:
    """Compile-plane view over one run's records: the per-shape AOT
    accounting ("compile" records, obs/memwatch.py), the cache-growth
    events the recompile watch caught, and the derived peak-HBM estimate
    the manifest carries."""
    out = {
        "shapes": [],          # one row per distinct dispatch shape
        "recompiles": [],      # jit executable-cache growth events
        "recompile_count": 0,
        "peak_hbm_bytes": None,
        "total_lower_s": None,
        "total_compile_s": None,
        "manifest_peak_hbm_bytes": None,
        "storm_events": 0,
    }
    for rec in records:
        kind = rec.get("kind")
        if kind == "manifest":
            if isinstance(rec.get("peak_hbm_bytes"), (int, float)):
                out["manifest_peak_hbm_bytes"] = rec["peak_hbm_bytes"]
        elif kind == "compile":
            if rec.get("event") == "recompile":
                out["recompiles"].append(
                    {k: rec.get(k) for k in ("step", "cache_size",
                                             "recompile_count",
                                             "compile_events")})
                if isinstance(rec.get("recompile_count"), (int, float)):
                    out["recompile_count"] = max(
                        out["recompile_count"], int(rec["recompile_count"]))
            else:
                out["shapes"].append(
                    {k: rec.get(k) for k in _COMPILE_ROW_FIELDS})
        elif kind == "event" and rec.get("rule") == "recompile_storm":
            out["storm_events"] += 1
    peaks = [s["peak_hbm_bytes"] for s in out["shapes"]
             if isinstance(s.get("peak_hbm_bytes"), (int, float))]
    if peaks:
        out["peak_hbm_bytes"] = max(peaks)
    for src, dst in (("lower_s", "total_lower_s"),
                     ("compile_s", "total_compile_s")):
        vals = [s[src] for s in out["shapes"]
                if isinstance(s.get(src), (int, float))]
        if vals:
            out[dst] = round(sum(vals), 4)
    return out


def format_compile(name: str, summary: dict) -> str:
    chunks = [f"compile: {name}"]
    shapes = summary["shapes"]
    if shapes:
        rows = []
        for s in shapes:
            key = str(s.get("shape_key") or "-")
            if len(key) > 40:
                key = key[:37] + "..."
            rows.append([
                "-" if s.get("shape_index") is None
                else str(s["shape_index"]),
                "-" if s.get("step") is None else _fmt(s["step"]),
                _fmt(s.get("flops")), _fmt(s.get("bytes_accessed")),
                _fmt(s.get("peak_hbm_bytes")), _fmt(s.get("temp_bytes")),
                _fmt(s.get("lower_s")), _fmt(s.get("compile_s")), key])
        chunks.append(f"\n[shapes] ({len(shapes)} distinct dispatch "
                      "shape(s))")
        chunks.append(_table(rows, ["idx", "step", "flops", "bytes_acc",
                                    "peak_hbm", "temp_bytes", "lower_s",
                                    "compile_s", "shape_key"]))
    else:
        chunks.append("no compile records (run without --obs-mem, or a "
                      "pre-memwatch run)")
    recompiles = summary["recompiles"]
    if recompiles:
        rows = [["-" if r.get("step") is None else _fmt(r["step"]),
                 _fmt(r.get("cache_size")), _fmt(r.get("recompile_count")),
                 _fmt(r.get("compile_events"))] for r in recompiles]
        chunks.append(f"\n[recompiles] ({len(recompiles)} cache-growth "
                      "event(s))")
        chunks.append(_table(rows, ["step", "cache_size",
                                    "recompile_count", "compile_events"]))
    tail = [f"recompile_count={summary['recompile_count']}"]
    if summary["storm_events"]:
        tail.append(f"recompile_storm events={summary['storm_events']}")
    if summary["peak_hbm_bytes"] is not None:
        tail.append(f"peak_hbm_bytes={_fmt(summary['peak_hbm_bytes'])}")
    if summary["manifest_peak_hbm_bytes"] is not None:
        tail.append("manifest peak_hbm_bytes="
                    f"{_fmt(summary['manifest_peak_hbm_bytes'])}")
    if summary["total_compile_s"] is not None:
        tail.append(f"total compile_s={_fmt(summary['total_compile_s'])}")
    chunks.append("\n" + "  ".join(tail))
    return "\n".join(chunks)


def run_compile(run: str, json_out: Optional[str] = None) -> int:
    """``compile`` subcommand: the per-shape AOT compile log and the
    recompile-watch events of one run."""
    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: {run}: skipped {bad} malformed line(s)")
    summary = summarize_compile(records)
    name = os.path.basename(os.path.normpath(run)) or run
    print(format_compile(name, summary))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump(summary, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def summarize_mem(records: Iterable[dict]) -> dict:
    """Memory-plane view over one run's records: the sampled "mem"
    window stream (live-array footprint, device memory_stats where the
    backend reports them) plus the three mem-plane anomaly rules."""
    out = {
        "samples": 0,
        "first_step": None, "last_step": None,
        "live_bytes_first": None, "live_bytes_last": None,
        "live_bytes_max": None, "live_count_last": None,
        "by_dtype": {},        # last sample's live bytes per dtype
        "bytes_in_use_last": None, "peak_bytes_in_use": None,
        "bytes_limit": None, "headroom_frac_max": None,
        "devices_reporting": None,
        "recompile_count": 0,
        "rules": {},           # mem-plane rule -> firings
    }
    for rec in records:
        kind = rec.get("kind")
        if kind == "event" and rec.get("rule") in _MEM_RULES:
            rule = str(rec["rule"])
            out["rules"][rule] = out["rules"].get(rule, 0) + 1
            continue
        if kind != "mem":
            continue
        out["samples"] += 1
        step = rec.get("step")
        if isinstance(step, (int, float)):
            if out["first_step"] is None:
                out["first_step"] = step
            out["last_step"] = step
        lb = rec.get("live_bytes")
        if isinstance(lb, (int, float)):
            if out["live_bytes_first"] is None:
                out["live_bytes_first"] = lb
            out["live_bytes_last"] = lb
            out["live_bytes_max"] = (lb if out["live_bytes_max"] is None
                                     else max(out["live_bytes_max"], lb))
        if isinstance(rec.get("live_count"), (int, float)):
            out["live_count_last"] = rec["live_count"]
        out["by_dtype"] = {
            k[len("live_bytes_"):]: v for k, v in rec.items()
            if k.startswith("live_bytes_") and isinstance(v, (int, float))
        } or out["by_dtype"]
        if isinstance(rec.get("bytes_in_use"), (int, float)):
            out["bytes_in_use_last"] = rec["bytes_in_use"]
        if isinstance(rec.get("bytes_limit"), (int, float)):
            out["bytes_limit"] = rec["bytes_limit"]
        if isinstance(rec.get("peak_bytes_in_use"), (int, float)):
            out["peak_bytes_in_use"] = max(
                out["peak_bytes_in_use"] or 0, rec["peak_bytes_in_use"])
        if isinstance(rec.get("headroom_frac"), (int, float)):
            out["headroom_frac_max"] = max(
                out["headroom_frac_max"] or 0.0, rec["headroom_frac"])
        if isinstance(rec.get("devices_reporting"), (int, float)):
            out["devices_reporting"] = rec["devices_reporting"]
        if isinstance(rec.get("recompile_count"), (int, float)):
            out["recompile_count"] = max(out["recompile_count"],
                                         int(rec["recompile_count"]))
    return out


def format_mem(name: str, summary: dict, compile_summary: dict) -> str:
    chunks = [f"mem: {name}"]
    n = summary["samples"]
    if n:
        grew = None
        if (summary["live_bytes_first"] is not None
                and summary["live_bytes_last"] is not None):
            grew = summary["live_bytes_last"] - summary["live_bytes_first"]
        chunks.append(
            f"live arrays: {n} sample(s) over steps "
            f"[{_fmt(summary['first_step'])}, {_fmt(summary['last_step'])}]"
            f"  bytes {_fmt(summary['live_bytes_first'])} -> "
            f"{_fmt(summary['live_bytes_last'])}"
            + ("" if grew is None else f" (delta {_fmt(grew)})")
            + ("" if summary["live_count_last"] is None
               else f"  count={_fmt(summary['live_count_last'])}"))
        if summary["by_dtype"]:
            rows = [[dtype, _fmt(b)] for dtype, b in
                    sorted(summary["by_dtype"].items(),
                           key=lambda kv: -kv[1])]
            chunks.append("\n[footprint by dtype] (last sample)")
            chunks.append(_table(rows, ["dtype", "live_bytes"]))
        if summary["bytes_in_use_last"] is not None:
            chunks.append(
                f"\ndevice: bytes_in_use={_fmt(summary['bytes_in_use_last'])}"
                f" peak={_fmt(summary['peak_bytes_in_use'])}"
                f" limit={_fmt(summary['bytes_limit'])}"
                f" headroom_frac_max={_fmt(summary['headroom_frac_max'])}"
                f" over {_fmt(summary['devices_reporting'])} device(s)")
        else:
            chunks.append("\ndevice: no memory_stats (backend does not "
                          "report them; live_arrays-only view)")
    else:
        chunks.append("no mem records (run without --obs-mem, or a "
                      "pre-memwatch run)")
    shapes = compile_summary["shapes"]
    if shapes:
        rows = []
        for s in shapes:
            rows.append(["-" if s.get("shape_index") is None
                         else str(s["shape_index"]),
                         "-" if s.get("step") is None else _fmt(s["step"]),
                         _fmt(s.get("peak_hbm_bytes")),
                         _fmt(s.get("temp_bytes")),
                         _fmt(s.get("argument_bytes")),
                         _fmt(s.get("output_bytes")),
                         _fmt(s.get("compile_s"))])
        chunks.append(f"\n[compile] ({len(shapes)} dispatch shape(s), "
                      f"recompile_count="
                      f"{compile_summary['recompile_count']})")
        chunks.append(_table(rows, ["idx", "step", "peak_hbm",
                                    "temp_bytes", "arg_bytes", "out_bytes",
                                    "compile_s"]))
    rules = summary["rules"]
    if rules:
        chunks.append("\nmem-plane anomalies: " + "  ".join(
            f"{rule}={cnt}" for rule, cnt in sorted(rules.items())))
    elif n or shapes:
        chunks.append("\nmem-plane anomalies: none "
                      f"({', '.join(_MEM_RULES)} all quiet)")
    return "\n".join(chunks)


def run_mem(run: str, json_out: Optional[str] = None) -> int:
    """``mem`` subcommand: one run's live-memory footprint (sampled
    "mem" windows + per-dtype breakdown), its per-shape compile log, and
    the leak/headroom/storm rule summary."""
    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: {run}: skipped {bad} malformed line(s)")
    summary = summarize_mem(records)
    comp = summarize_compile(records)
    name = os.path.basename(os.path.normpath(run)) or run
    print(format_mem(name, summary, comp))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump({"mem": summary, "compile": comp}, fh, indent=1,
                      sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "gate":
        gargs = build_gate_argparser().parse_args(argv[1:])
        return run_gate(gargs.run, gargs.baseline, gargs.write)
    if argv and argv[0] == "attr":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report attr",
            description="Print the paper's T_compute/T_select/T_comm "
                        "decomposition from a run's attr records or "
                        "straight from a jax.profiler trace.")
        ap.add_argument("target",
                        help="an --out-dir / metrics.jsonl with attr "
                             "records, or a profiler trace dir/file")
        ap.add_argument("--mode", default=None,
                        help="mode label stamped on a trace-derived record")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_attr(a.target, mode=a.mode, json_out=a.json_out)
    if argv and argv[0] == "events":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report events",
            description="Summarize a run's anomaly event stream per rule "
                        "(first/last step, count, last value).")
        ap.add_argument("run")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_events(a.run, json_out=a.json_out)
    if argv and argv[0] == "recovery":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report recovery",
            description="Summarize a run's resilience records: injected "
                        "faults, recovery actions, claimed vs unclaimed "
                        "anomaly events, final status.")
        ap.add_argument("run")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_recovery(a.run, json_out=a.json_out)
    if argv and argv[0] == "timeline":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report timeline",
            description="Rebuild and validate a chrome-trace timeline "
                        "from a run's metrics.jsonl.")
        ap.add_argument("run")
        ap.add_argument("--out", default=None,
                        help="output path (default: <run>/timeline.json)")
        a = ap.parse_args(argv[1:])
        return run_timeline(a.run, out=a.out)
    if argv and argv[0] == "fleet":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report fleet",
            description="Merge per-rank metric shards into per-step "
                        "cross-rank stats (min/median/max/std + skew) "
                        "with slowest-rank straggler attribution.")
        ap.add_argument("targets", nargs="+",
                        help="run dirs holding metrics.rank*.jsonl (or "
                             "metrics.jsonl), or shard paths")
        ap.add_argument("--kinds", default=None,
                        help="comma-separated source kinds to merge "
                             "(default: obs,train,spans)")
        ap.add_argument("--json", dest="json_out", default=None)
        ap.add_argument("--allow-mismatch", action="store_true",
                        help="merge shards even when their manifest "
                             "config_hash differs (normally refused)")
        a = ap.parse_args(argv[1:])
        kinds = ([k.strip() for k in a.kinds.split(",") if k.strip()]
                 if a.kinds else None)
        return run_fleet(a.targets, kinds, json_out=a.json_out,
                         allow_mismatch=a.allow_mismatch)
    if argv and argv[0] == "critpath":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report critpath",
            description="Join per-rank critpath stage-interval records "
                        "into the global per-step critical path: which "
                        "(rank, stage) bounds each step, per-rank "
                        "stage/wait budgets, modal-path summary.")
        ap.add_argument("targets", nargs="+",
                        help="run dirs holding metrics.rank*.jsonl (or "
                             "metrics.jsonl), or shard paths")
        ap.add_argument("--json", dest="json_out", default=None)
        ap.add_argument("--allow-mismatch", action="store_true",
                        help="merge shards even when their manifest "
                             "config_hash differs (normally refused)")
        ap.add_argument("--halt-on", default=None,
                        choices=("warn", "error"),
                        help="exit HALT_EXIT_CODE when the "
                             "critpath_shift rule fires at (or above) "
                             "this severity, like --obs-halt-on")
        a = ap.parse_args(argv[1:])
        return run_critpath(a.targets, json_out=a.json_out,
                            allow_mismatch=a.allow_mismatch,
                            halt_on=a.halt_on)
    if argv and argv[0] == "goodput":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report goodput",
            description="Per-rank goodput/badput decomposition: what "
                        "fraction of each rank's wall-clock advanced "
                        "training, where the rest went (select/comm/"
                        "wait/compile/ckpt/wasted/degraded/data/"
                        "startup/other), and the whole-fleet roll-up.")
        ap.add_argument("targets", nargs="+",
                        help="run dirs holding metrics.rank*.jsonl (or "
                             "metrics.jsonl), or shard paths")
        ap.add_argument("--compare", default=None,
                        help="second run to diff fleet decompositions "
                             "against (chaos vs clean)")
        ap.add_argument("--advise", action="store_true",
                        help="print the eviction hint: the rank whose "
                             "badput drags furthest below the fleet "
                             "median goodput_frac, and the recoverable "
                             "rank-seconds")
        ap.add_argument("--json", dest="json_out", default=None)
        ap.add_argument("--allow-mismatch", action="store_true",
                        help="merge shards even when their manifest "
                             "config_hash differs (normally refused)")
        a = ap.parse_args(argv[1:])
        return run_goodput(a.targets, json_out=a.json_out,
                           allow_mismatch=a.allow_mismatch,
                           advise=a.advise, compare=a.compare)
    if argv and argv[0] == "watch":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report watch",
            description="Tail-follow live shards with a refreshing "
                        "per-rank summary (Ctrl-C to stop).")
        ap.add_argument("targets", nargs="+",
                        help="run dirs or shard paths to follow")
        ap.add_argument("--interval", type=float, default=2.0,
                        help="seconds between polls (default 2)")
        ap.add_argument("--iterations", type=int, default=None,
                        help="stop after N polls (default: forever)")
        a = ap.parse_args(argv[1:])
        return run_watch(a.targets, interval=a.interval,
                         iterations=a.iterations)
    if argv and argv[0] == "plan":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report plan",
            description="Print the comm-planner decision: chosen wire "
                        "plan, every candidate's modeled score, and the "
                        "alpha-beta inputs (parallel/planner.py).")
        ap.add_argument("run", help="run dir or record file")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_plan(a.run, json_out=a.json_out)
    if argv and argv[0] == "compile":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report compile",
            description="Print a run's per-shape AOT compile log "
                        "(flops, bytes accessed, peak-HBM estimate, "
                        "wall times) and the recompile-watch events "
                        "(obs/memwatch.py).")
        ap.add_argument("run", help="run dir or record file")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_compile(a.run, json_out=a.json_out)
    if argv and argv[0] == "mem":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report mem",
            description="Print a run's live-memory footprint (sampled "
                        "mem windows, per-dtype breakdown, device "
                        "memory_stats), per-shape compile log, and the "
                        "leak/headroom/storm anomaly summary.")
        ap.add_argument("run", help="run dir or record file")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_mem(a.run, json_out=a.json_out)
    if argv and argv[0] == "ledger":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report ledger",
            description="Join measured per-step comm (attr t_comm_us, "
                        "obs wire_bytes) against the alpha-beta scaling "
                        "model; ratios ~1 mean the model explains the "
                        "wire.")
        ap.add_argument("targets", nargs="+",
                        help="run dirs or record files (fleet dirs ok)")
        ap.add_argument("--alpha-ms", type=float, default=None,
                        help="per-message latency override (default: "
                             "newest dcn_probe artifact, else 0)")
        ap.add_argument("--beta-gbps", type=float, default=None,
                        help="slow-link bandwidth override (default: "
                             "newest dcn_probe artifact, else 25)")
        ap.add_argument("--probe-dir", default=None,
                        help="where to look for dcn_probe_*proc.json "
                             "(default benchmarks/results/)")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_ledger(a.targets, json_out=a.json_out,
                          alpha_ms=a.alpha_ms, beta_gbps=a.beta_gbps,
                          probe_dir=a.probe_dir)
    if argv and argv[0] == "linkmap":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report linkmap",
            description="Join per-rank linkmap records into the fleet "
                        "network weather map: per-(axis, peer) EWMA "
                        "latency/bandwidth, worst link vs fleet median, "
                        "per-axis calib fits (obs/linkmap.py).")
        ap.add_argument("targets", nargs="+",
                        help="run dirs or record files (fleet dirs ok)")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_linkmap(a.targets, json_out=a.json_out)
    if argv and argv[0] == "forecast":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report forecast",
            description="Scale-out forecast view (obs/forecast.py): "
                        "hindcast error vs the run's own measured step "
                        "time, the per-P recommendation grid with "
                        "uncertainty bands, and the tree->balanced "
                        "crossover P.")
        ap.add_argument("targets", nargs="+",
                        help="run dirs or record files (fleet dirs ok)")
        ap.add_argument("--targets-p", dest="forecast_targets",
                        default=None, metavar="LIST",
                        help="comma-separated modeled worker counts "
                             "(default 32,256,1024, or the run's own "
                             "forecast records)")
        ap.add_argument("--probe-dir", default=None,
                        help="where to look for fit artifacts when the "
                             "stream has no calib records (default "
                             "benchmarks/results/)")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_forecast(a.targets, json_out=a.json_out,
                            search_dir=a.probe_dir,
                            forecast_targets=a.forecast_targets)
    if argv and argv[0] == "history":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report history",
            description="Cross-run trend table from a workspace registry "
                        "(runs.jsonl appended by --registry; "
                        "obs/registry.py).")
        ap.add_argument("registry", help="registry dir holding runs.jsonl")
        ap.add_argument("--config-hash", default=None,
                        help="only entries of this manifest config_hash")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_history(a.registry, config_hash=a.config_hash,
                           json_out=a.json_out)
    if argv and argv[0] == "regress":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report regress",
            description="Gate the run under test against the most recent "
                        "same-config registry entry with per-field rtol "
                        "drift checks; exit 0 pass / 1 regression / 2 "
                        "usage, like 'gate'.")
        ap.add_argument("run", help="an --out-dir or metrics.jsonl path")
        ap.add_argument("--registry", required=True,
                        help="registry dir holding runs.jsonl")
        ap.add_argument("--allow-mismatch", action="store_true",
                        help="fall back to the newest entry of ANY "
                             "config_hash when none matches (normally "
                             "refused: cross-config comparison)")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_regress(a.run, a.registry,
                           allow_mismatch=a.allow_mismatch,
                           json_out=a.json_out)
    args = build_argparser().parse_args(argv)
    if len(args.runs) > 2:
        print("at most 2 runs (one to summarize, two to compare)")
        return 2
    kinds = ([k.strip() for k in args.kinds.split(",") if k.strip()]
             if args.kinds else None)
    summaries, names, all_records = [], [], []
    for run in args.runs:
        try:
            records, bad = load_records(run)
        except OSError as e:
            print(f"cannot read {run}: {e}")
            return 2
        names.append(os.path.basename(os.path.normpath(run)) or run)
        summaries.append(summarize(records))
        all_records.append(records)
        if bad:
            print(f"note: {run}: skipped {bad} malformed line(s)")
        unknown = unregistered_kinds(records)
        if unknown:
            print(f"note: {run}: unregistered kind(s) "
                  f"{', '.join(unknown)} (not in utils.metrics.KINDS)")
    if len(summaries) == 1:
        manifest = extract_manifest(all_records[0])
        layers = summarize_layers(all_records[0])
        payload = {"run": names[0], "summary": summaries[0],
                   "manifest": manifest, "layers": layers}
        print(format_summary(names[0], summaries[0], kinds))
        if manifest and (not kinds or "manifest" in kinds):
            print()
            print(format_manifest(manifest))
        if layers and (not kinds or "layers" in kinds):
            print()
            print(format_layers(layers))
    else:
        diff = compare(summaries[0], summaries[1])
        payload = {
            "run_a": names[0], "run_b": names[1],
            "summary_a": summaries[0], "summary_b": summaries[1],
            "diff": diff,
        }
        print(format_compare(names[0], names[1], diff, kinds))
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
        print(f"\nwrote {args.json_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
