"""Metrics report CLI: aggregate metrics.jsonl runs, compare two of them,
or gate one against a committed baseline.

    python -m gtopkssgd_tpu.obs.report <run>            # summarize one run
    python -m gtopkssgd_tpu.obs.report <runA> <runB>    # side-by-side diff
    python -m gtopkssgd_tpu.obs.report <run> --json out.json
    python -m gtopkssgd_tpu.obs.report gate <run> --baseline base.json
    python -m gtopkssgd_tpu.obs.report attr <run|trace> # T_compute/T_select/
                                                        # T_comm decomposition
    python -m gtopkssgd_tpu.obs.report events <run>     # anomaly events by rule
    python -m gtopkssgd_tpu.obs.report timeline <run>   # rebuild timeline.json

A <run> is a directory containing metrics.jsonl (what --out-dir produces)
or a path to any .jsonl file of MetricsLogger records. Records group by
their ``kind`` ("train", "eval", "obs", "spans", "epoch", ...); every
numeric field gets count/mean/min/max/last. When the run has a manifest
header it is printed first, and "layers" records additionally get a
per-layer breakdown table (one row per layer, mean of each
counters.LAYER_FIELDS column). The two-run mode prints mean vs. mean with
a signed delta per field — the bench-regression triage view (was r05
slower because comm grew, or because achieved density drifted?).

``gate`` is the regression gate: the baseline JSON carries a ``checks``
list ({kind, field, stat, expect, rtol, atol, optional layer}) and an
optional ``manifest`` dict of exact-match provenance keys; a check passes
iff |actual - expect| <= atol + rtol*|expect|. Exit 0 = all pass, 1 = any
regression (or a checked field missing from the run), 2 = usage error.
``--write`` re-stamps the baseline's expectations from the run under test
(the regeneration path after an intentional behavior change).

Malformed lines are counted and skipped, never fatal: a run killed by the
stall watchdog (or the kernel) may leave a torn final line, and the whole
point of the report is reading evidence out of exactly such runs.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Bookkeeping fields that are not measurements; excluded from aggregation.
_META_FIELDS = {"kind", "time", "rank"}


def resolve_path(run: str) -> str:
    """<run dir> -> its metrics.jsonl; a file path passes through."""
    if os.path.isdir(run):
        return os.path.join(run, "metrics.jsonl")
    return run


def load_records(run: str) -> Tuple[List[dict], int]:
    """Parse a run's records. Returns (records, n_malformed)."""
    path = resolve_path(run)
    records, bad = [], 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                bad += 1
    return records, bad


def summarize(records: Iterable[dict]) -> Dict[str, Dict[str, dict]]:
    """{kind: {field: {count, mean, min, max, last}}} over numeric fields."""
    acc: Dict[str, Dict[str, List[float]]] = {}
    for rec in records:
        kind = str(rec.get("kind", "?"))
        if kind == "manifest":
            continue  # provenance header, not a measurement stream
        fields = acc.setdefault(kind, {})
        for key, val in rec.items():
            if key in _META_FIELDS:
                continue
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            fields.setdefault(key, []).append(float(val))
    out: Dict[str, Dict[str, dict]] = {}
    for kind, fields in acc.items():
        out[kind] = {}
        for key, vals in fields.items():
            out[kind][key] = {
                "count": len(vals),
                "mean": sum(vals) / len(vals),
                "min": min(vals),
                "max": max(vals),
                "last": vals[-1],
            }
    return out


def extract_manifest(records: Iterable[dict]) -> Optional[dict]:
    """The run's manifest record (kind "manifest"), or None. First wins:
    the trainer writes it before any measurement record."""
    for rec in records:
        if rec.get("kind") == "manifest":
            return rec
    return None


def summarize_layers(records: Iterable[dict]) -> Dict[str, Dict[str, dict]]:
    """{layer: {field: {count, mean, min, max, last}}} over the numeric
    fields of kind=="layers" records (the per-layer telemetry stream)."""
    by_layer: Dict[str, List[dict]] = {}
    for rec in records:
        if rec.get("kind") != "layers":
            continue
        by_layer.setdefault(str(rec.get("layer", "?")), []).append(rec)
    return {
        layer: summarize(recs).get("layers", {})
        for layer, recs in by_layer.items()
    }


def format_manifest(man: dict) -> str:
    rows = [
        [key, json.dumps(val) if isinstance(val, dict) else str(val)]
        for key, val in man.items()
        if key not in _META_FIELDS
    ]
    return "[manifest]\n" + _table(rows, ["key", "value"])


# Per-layer table column order; "layer" (the row key) and "step" are
# implicit. Mirrors counters.LAYER_FIELDS without importing jax here.
_LAYER_COLUMNS = ("density", "tau", "m_k", "residual_age", "residual_norm",
                  "grad_norm_pre", "grad_norm_post")


def format_layers(by_layer: Dict[str, Dict[str, dict]]) -> str:
    """One row per layer, mean of each per-layer counter over the run."""
    cols = [c for c in _LAYER_COLUMNS
            if any(c in fields for fields in by_layer.values())]
    rows = []
    for layer in sorted(by_layer):
        fields = by_layer[layer]
        rows.append([layer] + [
            _fmt(fields[c]["mean"]) if c in fields else "-" for c in cols
        ])
    n = max((max(s["count"] for s in f.values()) if f else 0)
            for f in by_layer.values())
    return (f"[layers] ({len(by_layer)} layers x {n} obs steps; "
            "mean per layer)\n"
            + _table(rows, ["layer"] + [f"mean({c})" for c in cols]))


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "nan"
    a = abs(v)
    if (a != 0 and a < 1e-3) or a >= 1e7:
        return f"{v:.4g}"
    if a >= 100 or v == int(v):
        return f"{v:.6g}"
    return f"{v:.4f}"


def _table(rows: List[Sequence[str]], header: Sequence[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows)
        for i in range(len(header))
    ]
    lines = []
    for r in [header, ["-" * w for w in widths]] + rows:
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


def format_summary(name: str, summary: Dict[str, Dict[str, dict]],
                   kinds: Optional[Sequence[str]] = None) -> str:
    chunks = [f"run: {name}"]
    for kind in sorted(summary):
        if kinds and kind not in kinds:
            continue
        fields = summary[kind]
        if not fields:
            continue
        n = max(s["count"] for s in fields.values())
        chunks.append(f"\n[{kind}] ({n} records)")
        rows = [
            [key, str(s["count"]), _fmt(s["mean"]), _fmt(s["min"]),
             _fmt(s["max"]), _fmt(s["last"])]
            for key, s in sorted(fields.items())
        ]
        chunks.append(
            _table(rows, ["field", "count", "mean", "min", "max", "last"]))
    return "\n".join(chunks)


def compare(a: Dict[str, Dict[str, dict]],
            b: Dict[str, Dict[str, dict]]) -> Dict[str, Dict[str, dict]]:
    """Per-kind/field mean-vs-mean diff for every field both runs have."""
    out: Dict[str, Dict[str, dict]] = {}
    for kind in sorted(set(a) & set(b)):
        fields = sorted(set(a[kind]) & set(b[kind]))
        if not fields:
            continue
        out[kind] = {}
        for key in fields:
            ma, mb = a[kind][key]["mean"], b[kind][key]["mean"]
            delta = mb - ma
            # A zero baseline has no meaningful relative change: record
            # None (rendered "—"), never a `+nan%` column; the absolute
            # delta still prints.
            pct = (delta / abs(ma) * 100.0) if ma else None
            out[kind][key] = {"mean_a": ma, "mean_b": mb,
                              "delta": delta, "delta_pct": pct}
    return out


def format_compare(name_a: str, name_b: str,
                   diff: Dict[str, Dict[str, dict]],
                   kinds: Optional[Sequence[str]] = None) -> str:
    chunks = [f"compare: A={name_a}  B={name_b}"]
    for kind in sorted(diff):
        if kinds and kind not in kinds:
            continue
        rows = []
        for key, d in sorted(diff[kind].items()):
            pct = d["delta_pct"]
            rows.append([
                key, _fmt(d["mean_a"]), _fmt(d["mean_b"]), _fmt(d["delta"]),
                ("—" if pct is None or pct != pct else f"{pct:+.1f}%"),
            ])
        if rows:
            chunks.append(f"\n[{kind}]")
            chunks.append(_table(
                rows, ["field", "mean_A", "mean_B", "delta", "delta%"]))
    return "\n".join(chunks)


def _lookup_stat(summary: Dict[str, Dict[str, dict]],
                 layers: Dict[str, Dict[str, dict]],
                 check: dict) -> Optional[float]:
    """Resolve one baseline check against a run's aggregates; None when
    the kind/layer/field/stat is absent (reported as a failure — a
    silently vanished counter IS a regression)."""
    stat = str(check.get("stat", "mean"))
    if check.get("layer") is not None:
        fields = layers.get(str(check["layer"]), {})
    else:
        fields = summary.get(str(check.get("kind", "obs")), {})
    entry = fields.get(str(check["field"]))
    if entry is None or stat not in entry:
        return None
    return float(entry[stat])


def _check_id(check: dict) -> str:
    where = (f"layers[{check['layer']}]" if check.get("layer") is not None
             else str(check.get("kind", "obs")))
    return f"{where}.{check['field']}.{check.get('stat', 'mean')}"


def run_gate(run: str, baseline_path: str,
             write: Optional[str] = None) -> int:
    """Diff a run against a committed baseline JSON; 0 pass / 1 fail."""
    try:
        with open(baseline_path) as fh:
            baseline = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"cannot read baseline {baseline_path}: {e}")
        return 2
    checks = baseline.get("checks")
    if not isinstance(checks, list) or not checks:
        print(f"baseline {baseline_path} has no 'checks' list")
        return 2
    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: {run}: skipped {bad} malformed line(s)")
    summary = summarize(records)
    layers = summarize_layers(records)
    manifest = extract_manifest(records) or {}

    failures = 0
    rows = []
    for key, expect in sorted((baseline.get("manifest") or {}).items()):
        actual = manifest.get(key)
        ok = actual == expect
        failures += not ok
        rows.append([f"manifest.{key}", json.dumps(expect),
                     json.dumps(actual), "-", "OK" if ok else "FAIL"])
    for check in checks:
        expect = float(check["expect"])
        rtol = float(check.get("rtol", 0.0))
        atol = float(check.get("atol", 0.0))
        tol = atol + rtol * abs(expect)
        actual = _lookup_stat(summary, layers, check)
        if actual is None:
            failures += 1
            rows.append([_check_id(check), _fmt(expect), "missing",
                         _fmt(tol), "FAIL"])
            continue
        ok = abs(actual - expect) <= tol
        failures += not ok
        rows.append([_check_id(check), _fmt(expect), _fmt(actual),
                     _fmt(tol), "OK" if ok else "FAIL"])
    print(f"gate: run={run}  baseline={baseline_path}")
    print(_table(rows, ["check", "expect", "actual", "tol", "status"]))
    print(f"gate: {len(rows) - failures}/{len(rows)} checks passed")

    if write:
        # Regeneration path: keep each check's spec (tolerances, stat,
        # addressing) but re-stamp 'expect' from the run under test, and
        # refresh the pinned manifest keys. Review the diff like code.
        new_checks = []
        for check in checks:
            actual = _lookup_stat(summary, layers, check)
            out = dict(check)
            if actual is not None:
                out["expect"] = actual
            new_checks.append(out)
        new_base = dict(baseline)
        new_base["checks"] = new_checks
        if baseline.get("manifest"):
            new_base["manifest"] = {
                key: manifest.get(key) for key in baseline["manifest"]
            }
        with open(write, "w") as fh:
            json.dump(new_base, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {write}")
    return 1 if failures else 0


def _is_run(target: str) -> bool:
    """Does the target look like a metrics run (vs. a profiler trace)?"""
    if os.path.isdir(target):
        return os.path.exists(os.path.join(target, "metrics.jsonl"))
    return target.endswith(".jsonl")


def run_attr(target: str, mode: Optional[str] = None,
             json_out: Optional[str] = None) -> int:
    """``attr`` subcommand: print the paper's T_compute/T_select/T_comm
    table. The target is either a run (metrics.jsonl carrying logged
    "attr" records — the gate smoke writes one) or a profiler trace
    dir/file, which is parsed and attributed on the spot."""
    from gtopkssgd_tpu.obs import trace_attr

    if _is_run(target):
        try:
            records, bad = load_records(target)
        except OSError as e:
            print(f"cannot read {target}: {e}")
            return 2
        recs = [{k: v for k, v in r.items() if k not in _META_FIELDS}
                for r in records if r.get("kind") == "attr"]
        if not recs:
            print(f"{target}: no attr records (pass a trace dir, or log "
                  "one via obs.trace_attr.attribute)")
            return 1
    else:
        try:
            recs = [trace_attr.attribute(target, mode=mode)]
        except (FileNotFoundError, OSError, ValueError) as e:
            print(f"cannot attribute {target}: {e}")
            return 2
    for rec in recs:
        print(trace_attr.format_attr(rec))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump(recs if len(recs) > 1 else recs[0], fh, indent=1,
                      sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def summarize_events(records: Iterable[dict]) -> Dict[str, dict]:
    """{rule: {severity, count, first_step, last_step, last_value,
    threshold, last_message}} over kind=="event" records."""
    by_rule: Dict[str, dict] = {}
    for rec in records:
        if rec.get("kind") != "event":
            continue
        rule = str(rec.get("rule", "?"))
        r = by_rule.setdefault(rule, {
            "severity": rec.get("severity"), "count": 0,
            "first_step": None, "last_step": None, "last_value": None,
            "threshold": rec.get("threshold"), "last_message": None,
        })
        r["count"] += 1
        r["severity"] = rec.get("severity", r["severity"])
        step = rec.get("step")
        if isinstance(step, (int, float)):
            r["first_step"] = (step if r["first_step"] is None
                               else min(r["first_step"], step))
            r["last_step"] = (step if r["last_step"] is None
                              else max(r["last_step"], step))
        r["last_value"] = rec.get("value", r["last_value"])
        r["threshold"] = rec.get("threshold", r["threshold"])
        r["last_message"] = rec.get("message", r["last_message"])
    return by_rule


def format_events(name: str, by_rule: Dict[str, dict]) -> str:
    if not by_rule:
        return f"events: {name}: none recorded"
    rows = []
    for rule in sorted(by_rule):
        r = by_rule[rule]
        rows.append([
            rule, str(r["severity"]), str(r["count"]),
            "-" if r["first_step"] is None else _fmt(r["first_step"]),
            "-" if r["last_step"] is None else _fmt(r["last_step"]),
            "-" if r["last_value"] is None else _fmt(r["last_value"]),
            "-" if r["threshold"] is None else _fmt(r["threshold"]),
        ])
    out = [f"events: {name}",
           _table(rows, ["rule", "severity", "count", "first_step",
                         "last_step", "last_value", "threshold"])]
    for rule in sorted(by_rule):
        msg = by_rule[rule]["last_message"]
        if msg:
            out.append(f"  {rule}: {msg}")
    return "\n".join(out)


def run_events(run: str, json_out: Optional[str] = None) -> int:
    """``events`` subcommand: summarize a run's anomaly stream per rule."""
    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: {run}: skipped {bad} malformed line(s)")
    by_rule = summarize_events(records)
    name = os.path.basename(os.path.normpath(run)) or run
    print(format_events(name, by_rule))
    if json_out:
        with open(json_out, "w") as fh:
            json.dump(by_rule, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"wrote {json_out}")
    return 0


def run_timeline(run: str, out: Optional[str] = None) -> int:
    """``timeline`` subcommand: rebuild a chrome-trace timeline from a
    run's metrics.jsonl (markers + counter tracks at recorded wall-clock
    times), validate it, and write it next to the run."""
    from gtopkssgd_tpu.obs.timeline import (
        timeline_from_records,
        validate_timeline,
    )

    try:
        records, bad = load_records(run)
    except OSError as e:
        print(f"cannot read {run}: {e}")
        return 2
    if bad:
        print(f"note: {run}: skipped {bad} malformed line(s)")
    name = os.path.basename(os.path.normpath(run)) or run
    doc = timeline_from_records(records, label=name)
    problems = validate_timeline(doc)
    if out is None:
        base = run if os.path.isdir(run) else os.path.dirname(run) or "."
        out = os.path.join(base, "timeline.json")
    with open(out, "w") as fh:
        json.dump(doc, fh)
        fh.write("\n")
    n = sum(1 for e in doc["traceEvents"] if e.get("ph") != "M")
    print(f"timeline: {name}: {n} events -> {out}"
          + (" (open in chrome://tracing or ui.perfetto.dev)"))
    for p in problems:
        print(f"invalid: {p}")
    return 1 if problems else 0


def build_gate_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "gtopkssgd_tpu.obs.report gate",
        description="Diff a run against a committed baseline JSON; exit "
                    "nonzero on regression.",
    )
    p.add_argument("run", help="an --out-dir or a metrics.jsonl path")
    p.add_argument("--baseline", required=True,
                   help="baseline JSON with a 'checks' list and optional "
                        "'manifest' exact-match dict")
    p.add_argument("--write", default=None,
                   help="write a regenerated baseline (same check specs, "
                        "expectations re-stamped from this run) here")
    return p


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "gtopkssgd_tpu.obs.report",
        description="Aggregate metrics.jsonl runs; compare two for "
                    "regression triage.",
    )
    p.add_argument("runs", nargs="+",
                   help="1 or 2 runs: an --out-dir (containing "
                        "metrics.jsonl) or a .jsonl path")
    p.add_argument("--kinds", default=None,
                   help="comma-separated record kinds to report "
                        "(default: all present)")
    p.add_argument("--json", dest="json_out", default=None,
                   help="also write the aggregate (or diff) as JSON here")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "gate":
        gargs = build_gate_argparser().parse_args(argv[1:])
        return run_gate(gargs.run, gargs.baseline, gargs.write)
    if argv and argv[0] == "attr":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report attr",
            description="Print the paper's T_compute/T_select/T_comm "
                        "decomposition from a run's attr records or "
                        "straight from a jax.profiler trace.")
        ap.add_argument("target",
                        help="an --out-dir / metrics.jsonl with attr "
                             "records, or a profiler trace dir/file")
        ap.add_argument("--mode", default=None,
                        help="mode label stamped on a trace-derived record")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_attr(a.target, mode=a.mode, json_out=a.json_out)
    if argv and argv[0] == "events":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report events",
            description="Summarize a run's anomaly event stream per rule "
                        "(first/last step, count, last value).")
        ap.add_argument("run")
        ap.add_argument("--json", dest="json_out", default=None)
        a = ap.parse_args(argv[1:])
        return run_events(a.run, json_out=a.json_out)
    if argv and argv[0] == "timeline":
        ap = argparse.ArgumentParser(
            "gtopkssgd_tpu.obs.report timeline",
            description="Rebuild and validate a chrome-trace timeline "
                        "from a run's metrics.jsonl.")
        ap.add_argument("run")
        ap.add_argument("--out", default=None,
                        help="output path (default: <run>/timeline.json)")
        a = ap.parse_args(argv[1:])
        return run_timeline(a.run, out=a.out)
    args = build_argparser().parse_args(argv)
    if len(args.runs) > 2:
        print("at most 2 runs (one to summarize, two to compare)")
        return 2
    kinds = ([k.strip() for k in args.kinds.split(",") if k.strip()]
             if args.kinds else None)
    summaries, names, all_records = [], [], []
    for run in args.runs:
        try:
            records, bad = load_records(run)
        except OSError as e:
            print(f"cannot read {run}: {e}")
            return 2
        names.append(os.path.basename(os.path.normpath(run)) or run)
        summaries.append(summarize(records))
        all_records.append(records)
        if bad:
            print(f"note: {run}: skipped {bad} malformed line(s)")
    if len(summaries) == 1:
        manifest = extract_manifest(all_records[0])
        layers = summarize_layers(all_records[0])
        payload = {"run": names[0], "summary": summaries[0],
                   "manifest": manifest, "layers": layers}
        print(format_summary(names[0], summaries[0], kinds))
        if manifest and (not kinds or "manifest" in kinds):
            print()
            print(format_manifest(manifest))
        if layers and (not kinds or "layers" in kinds):
            print()
            print(format_layers(layers))
    else:
        diff = compare(summaries[0], summaries[1])
        payload = {
            "run_a": names[0], "run_b": names[1],
            "summary_a": summaries[0], "summary_b": summaries[1],
            "diff": diff,
        }
        print(format_compare(names[0], names[1], diff, kinds))
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
        print(f"\nwrote {args.json_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
