"""Metrics report CLI: aggregate metrics.jsonl runs, compare two of them.

    python -m gtopkssgd_tpu.obs.report <run>            # summarize one run
    python -m gtopkssgd_tpu.obs.report <runA> <runB>    # side-by-side diff
    python -m gtopkssgd_tpu.obs.report <run> --json out.json

A <run> is a directory containing metrics.jsonl (what --out-dir produces)
or a path to any .jsonl file of MetricsLogger records. Records group by
their ``kind`` ("train", "eval", "obs", "spans", "epoch", ...); every
numeric field gets count/mean/min/max/last. The two-run mode prints mean
vs. mean with a signed delta per field — the bench-regression triage view
(was r05 slower because comm grew, or because achieved density drifted?).

Malformed lines are counted and skipped, never fatal: a run killed by the
stall watchdog (or the kernel) may leave a torn final line, and the whole
point of the report is reading evidence out of exactly such runs.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Bookkeeping fields that are not measurements; excluded from aggregation.
_META_FIELDS = {"kind", "time", "rank"}


def resolve_path(run: str) -> str:
    """<run dir> -> its metrics.jsonl; a file path passes through."""
    if os.path.isdir(run):
        return os.path.join(run, "metrics.jsonl")
    return run


def load_records(run: str) -> Tuple[List[dict], int]:
    """Parse a run's records. Returns (records, n_malformed)."""
    path = resolve_path(run)
    records, bad = [], 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                bad += 1
    return records, bad


def summarize(records: Iterable[dict]) -> Dict[str, Dict[str, dict]]:
    """{kind: {field: {count, mean, min, max, last}}} over numeric fields."""
    acc: Dict[str, Dict[str, List[float]]] = {}
    for rec in records:
        kind = str(rec.get("kind", "?"))
        fields = acc.setdefault(kind, {})
        for key, val in rec.items():
            if key in _META_FIELDS:
                continue
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            fields.setdefault(key, []).append(float(val))
    out: Dict[str, Dict[str, dict]] = {}
    for kind, fields in acc.items():
        out[kind] = {}
        for key, vals in fields.items():
            out[kind][key] = {
                "count": len(vals),
                "mean": sum(vals) / len(vals),
                "min": min(vals),
                "max": max(vals),
                "last": vals[-1],
            }
    return out


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "nan"
    a = abs(v)
    if (a != 0 and a < 1e-3) or a >= 1e7:
        return f"{v:.4g}"
    if a >= 100 or v == int(v):
        return f"{v:.6g}"
    return f"{v:.4f}"


def _table(rows: List[Sequence[str]], header: Sequence[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows)
        for i in range(len(header))
    ]
    lines = []
    for r in [header, ["-" * w for w in widths]] + rows:
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


def format_summary(name: str, summary: Dict[str, Dict[str, dict]],
                   kinds: Optional[Sequence[str]] = None) -> str:
    chunks = [f"run: {name}"]
    for kind in sorted(summary):
        if kinds and kind not in kinds:
            continue
        fields = summary[kind]
        if not fields:
            continue
        n = max(s["count"] for s in fields.values())
        chunks.append(f"\n[{kind}] ({n} records)")
        rows = [
            [key, str(s["count"]), _fmt(s["mean"]), _fmt(s["min"]),
             _fmt(s["max"]), _fmt(s["last"])]
            for key, s in sorted(fields.items())
        ]
        chunks.append(
            _table(rows, ["field", "count", "mean", "min", "max", "last"]))
    return "\n".join(chunks)


def compare(a: Dict[str, Dict[str, dict]],
            b: Dict[str, Dict[str, dict]]) -> Dict[str, Dict[str, dict]]:
    """Per-kind/field mean-vs-mean diff for every field both runs have."""
    out: Dict[str, Dict[str, dict]] = {}
    for kind in sorted(set(a) & set(b)):
        fields = sorted(set(a[kind]) & set(b[kind]))
        if not fields:
            continue
        out[kind] = {}
        for key in fields:
            ma, mb = a[kind][key]["mean"], b[kind][key]["mean"]
            delta = mb - ma
            pct = (delta / abs(ma) * 100.0) if ma else float("nan")
            out[kind][key] = {"mean_a": ma, "mean_b": mb,
                              "delta": delta, "delta_pct": pct}
    return out


def format_compare(name_a: str, name_b: str,
                   diff: Dict[str, Dict[str, dict]],
                   kinds: Optional[Sequence[str]] = None) -> str:
    chunks = [f"compare: A={name_a}  B={name_b}"]
    for kind in sorted(diff):
        if kinds and kind not in kinds:
            continue
        rows = []
        for key, d in sorted(diff[kind].items()):
            pct = d["delta_pct"]
            rows.append([
                key, _fmt(d["mean_a"]), _fmt(d["mean_b"]), _fmt(d["delta"]),
                ("nan" if pct != pct else f"{pct:+.1f}%"),
            ])
        if rows:
            chunks.append(f"\n[{kind}]")
            chunks.append(_table(
                rows, ["field", "mean_A", "mean_B", "delta", "delta%"]))
    return "\n".join(chunks)


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "gtopkssgd_tpu.obs.report",
        description="Aggregate metrics.jsonl runs; compare two for "
                    "regression triage.",
    )
    p.add_argument("runs", nargs="+",
                   help="1 or 2 runs: an --out-dir (containing "
                        "metrics.jsonl) or a .jsonl path")
    p.add_argument("--kinds", default=None,
                   help="comma-separated record kinds to report "
                        "(default: all present)")
    p.add_argument("--json", dest="json_out", default=None,
                   help="also write the aggregate (or diff) as JSON here")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_argparser().parse_args(argv)
    if len(args.runs) > 2:
        print("at most 2 runs (one to summarize, two to compare)")
        return 2
    kinds = ([k.strip() for k in args.kinds.split(",") if k.strip()]
             if args.kinds else None)
    summaries, names = [], []
    for run in args.runs:
        try:
            records, bad = load_records(run)
        except OSError as e:
            print(f"cannot read {run}: {e}")
            return 2
        names.append(os.path.basename(os.path.normpath(run)) or run)
        summaries.append(summarize(records))
        if bad:
            print(f"note: {run}: skipped {bad} malformed line(s)")
    if len(summaries) == 1:
        payload = {"run": names[0], "summary": summaries[0]}
        print(format_summary(names[0], summaries[0], kinds))
    else:
        diff = compare(summaries[0], summaries[1])
        payload = {
            "run_a": names[0], "run_b": names[1],
            "summary_a": summaries[0], "summary_b": summaries[1],
            "diff": diff,
        }
        print(format_compare(names[0], names[1], diff, kinds))
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True)
        print(f"\nwrote {args.json_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
