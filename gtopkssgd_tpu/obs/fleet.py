"""Fleet layer: merge per-rank metric shards, find the slow host.

gTop-k S-SGD's value proposition is behavior on low-bandwidth MULTI-worker
networks (arXiv:1901.04359), and synchronous SPMD step time is the max
over ranks — yet until this module every obs tool was single-rank-deep: a
``--multihost`` run produced one shard per process and nothing could merge
them, compare ranks, or name the straggler that dominates every step.
Ok-Topk (arXiv:2201.07598) and the top-k analysis paper (arXiv:1911.08772)
both identify cross-worker imbalance in selection/communication cost as
the first-order effect at scale; this is the layer that measures it.

Pieces (all host-side, stdlib-only — report-CLI friendly):

  find_shards / load_shards  deterministic shard discovery
      (``metrics.rank{r}.jsonl``, ``metrics.jsonl`` = rank 0) and parsing.
  validate_shards            join-key validation off the manifest headers
      every shard carries: a merge is refused when ``config_hash`` differs
      across shards (two different runs dumped into one dir is archaeology
      corruption, not a fleet).
  fleet_rows                 align records by (kind, step) across ranks
      into per-(step, field) rows with min/median/max/mean/std, the
      per-rank skew vector (value - median) and ``skew_max``; plus a
      ``lag_s`` row per step from the records' wall-clock arrival times —
      which host reached the sync point late, and by how much.
  straggler_rows             per-step slowest-rank attribution on top of
      the lag rows (which rank, how far behind the median) and
      persistent-vs-transient classification via a per-rank EWMA of lag,
      fed through AnomalyMonitor.observe_ranks so the
      ``straggler_persistent`` rule emits ordinary ``event`` records and
      ``--obs-halt-on`` covers it. Rows carry the slowest rank's local
      critical ``stage`` when that rank shipped critpath records — the
      difference between "rank 2 is late" and "rank 2 is late because
      its input pipeline (compute) is slow".
  goodput_rows               per-rank goodput/badput decomposition
      (obs/goodput.py fold: last cumulative ``goodput`` record per rank,
      synthesized from critpath/compile/recovery evidence when a rank
      shipped none) plus the whole-fleet wall-weighted decomposition —
      the "what fraction of this fleet's rank-seconds advanced
      training" view, and the input to ``report goodput --advise``.
  critpath_rows              join per-rank ``critpath`` stage-interval
      records (obs/critpath.py) by step into the GLOBAL critical path:
      per-step crit_rank/crit_stage/crit_frac + the (rank, stage) chain,
      per-rank on-chain stage budgets and blocked-time totals, fed
      through AnomalyMonitor.observe_critpath so the ``critpath_shift``
      rule emits ordinary ``event`` records and ``--obs-halt-on``
      covers a moved bottleneck.
  merge                      the one-call entry (report ``fleet``
      subcommand, gate smoke): shards in, rows + straggler attribution +
      critical-path join + fired events + the validated manifest out.

Ragged shards are first-class: a rank missing a step (crashed, still
catching up, thinned logging) drops out of that step's stats — ``n_ranks``
records how many actually contributed — and never aborts the merge.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from gtopkssgd_tpu.obs import critpath as _critpath
from gtopkssgd_tpu.obs import goodput as _goodput
from gtopkssgd_tpu.obs.events import AnomalyMonitor
from gtopkssgd_tpu.obs.report import extract_manifest, load_records
from gtopkssgd_tpu.utils.metrics import shard_filename, shard_rank

# Record kinds that carry a per-step stream worth merging across ranks.
# "layers" is excluded by default (per-layer x per-rank explodes row
# count); pass kinds=("layers",) explicitly to get it.
DEFAULT_KINDS = ("obs", "train", "spans")

# Fields that are bookkeeping, not per-rank measurements.
_SKIP_FIELDS = {"kind", "time", "rank", "step"}


def find_shards(target: str) -> Dict[int, str]:
    """{rank: path} for one target.

    A directory yields its ``metrics.rank{r}.jsonl`` shards, falling back
    to ``metrics.jsonl`` as rank 0 (single-process runs merge as a
    1-rank fleet — skew 0 by construction). A file path yields the rank
    encoded in its name, or rank 0 for non-shard names.
    """
    if os.path.isdir(target):
        shards = {}
        for name in sorted(os.listdir(target)):
            r = shard_rank(name)
            if r is not None:
                shards[r] = os.path.join(target, name)
        if not shards:
            single = os.path.join(target, "metrics.jsonl")
            if os.path.exists(single):
                shards[0] = single
        if not shards:
            raise FileNotFoundError(
                f"{target}: no metrics.rank*.jsonl shards and no "
                "metrics.jsonl")
        return shards
    r = shard_rank(target)
    return {r if r is not None else 0: target}


def resolve_targets(targets: Sequence[str]) -> Dict[int, str]:
    """Union of find_shards over many targets (dirs and/or files). Two
    targets claiming the same rank is a usage error — the caller is about
    to merge two different runs' shards under one join key."""
    shards: Dict[int, str] = {}
    for t in targets:
        for r, path in find_shards(t).items():
            if r in shards and os.path.abspath(shards[r]) != \
                    os.path.abspath(path):
                raise ValueError(
                    f"rank {r} appears twice ({shards[r]} and {path}); "
                    "merge one run's shards at a time")
            shards[r] = path
    return shards


def load_shards(shards: Mapping[int, str]
                ) -> Tuple[Dict[int, List[dict]], int]:
    """{rank: records} plus the total malformed-line count (torn final
    lines in killed runs are expected, never fatal)."""
    out, bad = {}, 0
    for r in sorted(shards):
        records, b = load_records(shards[r])
        out[r] = records
        bad += b
    return out, bad


def validate_shards(records_by_rank: Mapping[int, List[dict]],
                    allow_mismatch: bool = False) -> Optional[dict]:
    """Check every shard's manifest header agrees on ``config_hash`` (the
    full-config join key) and return the reference manifest. Shards
    without a manifest are tolerated (pre-manifest runs, hand-built
    fixtures); a HASH MISMATCH is refused — those shards are provably
    from different runs and any per-step comparison would be noise."""
    manifests = {r: extract_manifest(recs)
                 for r, recs in records_by_rank.items()}
    hashes = {r: m.get("config_hash") for r, m in manifests.items()
              if m is not None and m.get("config_hash")}
    if len(set(hashes.values())) > 1 and not allow_mismatch:
        detail = ", ".join(f"rank {r}: {h}" for r, h in sorted(hashes.items()))
        raise ValueError(
            f"config_hash mismatch across shards ({detail}); these are "
            "different runs — re-merge with matching shards (or "
            "allow_mismatch=True to force)")
    for m in manifests.values():
        if m is not None:
            return m
    return None


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _std(vals: Sequence[float], mean: float) -> float:
    if len(vals) < 2:
        return 0.0
    return math.sqrt(sum((v - mean) ** 2 for v in vals) / len(vals))


def _stats_row(src: str, step: float, field: str,
               per_rank: Dict[int, float], center: str = "median") -> dict:
    vals = list(per_rank.values())
    mean = sum(vals) / len(vals)
    med = _median(vals)
    ref = med if center == "median" else min(vals)
    skew = {f"r{r}": per_rank[r] - ref for r in sorted(per_rank)}
    return {
        "src": src, "step": step, "field": field,
        "n_ranks": len(per_rank),
        "min": min(vals), "median": med, "max": max(vals),
        "mean": mean, "std": _std(vals, mean),
        "skew": skew,
        "skew_max": max(abs(d) for d in skew.values()),
    }


def _index_by_step(records_by_rank: Mapping[int, List[dict]],
                   kinds: Sequence[str]
                   ) -> Dict[Tuple[str, float], Dict[int, dict]]:
    """{(kind, step): {rank: record}} — last record wins when a rank
    logged the same (kind, step) twice (restarted window)."""
    idx: Dict[Tuple[str, float], Dict[int, dict]] = {}
    for rank, records in records_by_rank.items():
        for rec in records:
            kind = rec.get("kind")
            step = rec.get("step")
            if kind not in kinds or not isinstance(step, (int, float)) \
                    or isinstance(step, bool):
                continue
            idx.setdefault((str(kind), float(step)), {})[rank] = rec
    return idx


def fleet_rows(records_by_rank: Mapping[int, List[dict]],
               kinds: Sequence[str] = DEFAULT_KINDS) -> List[dict]:
    """The merged view: one row per (src kind, step, field) with cross-
    rank min/median/max/mean/std and the per-rank skew vector, plus a
    ``lag_s`` row per (src kind, step) from record arrival times (value
    per rank = seconds behind the FIRST rank to log that step — the
    direct fingerprint of the host everyone else waited for)."""
    rows: List[dict] = []
    for (kind, step), per_rank in sorted(_index_by_step(
            records_by_rank, kinds).items()):
        fields = sorted({
            key for rec in per_rank.values() for key, val in rec.items()
            if key not in _SKIP_FIELDS and not isinstance(val, bool)
            and isinstance(val, (int, float))
        })
        for field in fields:
            vals = {r: float(rec[field]) for r, rec in per_rank.items()
                    if isinstance(rec.get(field), (int, float))
                    and not isinstance(rec.get(field), bool)}
            if vals:
                rows.append(_stats_row(kind, step, field, vals))
        times = {r: float(rec["time"]) for r, rec in per_rank.items()
                 if isinstance(rec.get("time"), (int, float))}
        if times:
            t0 = min(times.values())
            lags = {r: t - t0 for r, t in times.items()}
            rows.append(_stats_row(kind, step, "lag_s", lags, center="min"))
    return rows


def _arrival_times(records_by_rank: Mapping[int, List[dict]],
                   kind: str) -> Dict[float, Dict[int, float]]:
    out: Dict[float, Dict[int, float]] = {}
    for (k, step), per_rank in _index_by_step(
            records_by_rank, (kind,)).items():
        times = {r: float(rec["time"]) for r, rec in per_rank.items()
                 if isinstance(rec.get("time"), (int, float))}
        if times:
            out[step] = times
    return out


def pick_straggler_kind(records_by_rank: Mapping[int, List[dict]],
                        preferred: Sequence[str] = ("obs", "train")
                        ) -> Optional[str]:
    """The densest per-step stream present on >= 2 ranks wins — obs
    records usually fire more often than train records."""
    for kind in preferred:
        times = _arrival_times(records_by_rank, kind)
        if times and max(len(t) for t in times.values()) >= 2:
            return kind
    for kind in preferred:  # 1-rank fleet: still produce (empty-lag) rows
        if _arrival_times(records_by_rank, kind):
            return kind
    return None


def _goodput_by_rank(records_by_rank: Mapping[int, List[dict]]
                     ) -> Dict[int, List[dict]]:
    """{rank: [goodput records sorted by step]} — the cumulative ledger
    stream each rank shipped (possibly empty)."""
    out: Dict[int, List[dict]] = {}
    for rank, records in records_by_rank.items():
        recs = [r for r in records if r.get("kind") == "goodput"
                and isinstance(r.get("step"), (int, float))
                and not isinstance(r.get("step"), bool)]
        if recs:
            recs.sort(key=lambda r: float(r["step"]))
            out[rank] = recs
    return out


def _badput_at(gp_recs: Optional[List[dict]], step: float
               ) -> Tuple[Optional[str], Optional[float]]:
    """(dominant badput category, its wall fraction) from the latest
    cumulative goodput record at or before ``step`` (falling back to the
    rank's first record when the straggler row predates the first ledger
    log). (None, None) when the rank shipped no goodput records."""
    if not gp_recs:
        return None, None
    rec = gp_recs[0]
    for cand in gp_recs:
        if float(cand["step"]) <= step:
            rec = cand
        else:
            break
    cat = _goodput.dominant_badput(rec)
    if cat is None:
        return None, None
    return cat, _goodput.category_fracs(rec).get(cat)


def goodput_rows(records_by_rank: Mapping[int, List[dict]]
                 ) -> Tuple[List[dict], Dict[int, dict], Optional[dict]]:
    """Per-rank goodput/badput decomposition + the fleet roll-up.

    Returns (rows, decomp_by_rank, fleet). One row per rank: the folded
    end-of-run decomposition (obs/goodput.py ``fold`` — last cumulative
    ledger record, or a synthesis from critpath/compile/recovery
    evidence when the rank shipped none) plus its dominant badput
    category. ``fleet`` is the wall-weighted whole-fleet decomposition
    (None for an empty fleet) — the single number ("this fleet's
    rank-seconds were X% productive") and the input to ``advise``."""
    decomp_by_rank = _goodput.fold_shards(records_by_rank)
    rows: List[dict] = []
    for rank in sorted(decomp_by_rank):
        d = decomp_by_rank[rank]
        row = {"src": "goodput", "field": "goodput", "rank": rank,
               "badput": _goodput.dominant_badput(d)}
        row.update({k: v for k, v in d.items() if k not in row})
        rows.append(row)
    fleet = (_goodput.fleet_decomposition(decomp_by_rank)
             if decomp_by_rank else None)
    return rows, decomp_by_rank, fleet


def _linkmap_by_rank(records_by_rank: Mapping[int, List[dict]]
                     ) -> Dict[int, List[dict]]:
    """{rank: [linkmap records sorted by step]} — each rank's weather-
    map snapshots (possibly empty)."""
    out: Dict[int, List[dict]] = {}
    for rank, records in records_by_rank.items():
        recs = [r for r in records if r.get("kind") == "linkmap"
                and isinstance(r.get("step"), (int, float))
                and not isinstance(r.get("step"), bool)]
        if recs:
            recs.sort(key=lambda r: float(r["step"]))
            out[rank] = recs
    return out


def _slow_link_at(lm_recs: Optional[List[dict]], step: float
                  ) -> Tuple[Optional[str], Optional[float]]:
    """(worst link key, its EWMA-over-fleet-median factor) from the
    straggling rank's latest weather-map record at or before ``step``
    (falling back to its first record when the straggler row predates
    the first capture). (None, None) when the rank shipped no linkmap
    records — pre-linkmap shards merge unchanged."""
    if not lm_recs:
        return None, None
    rec = lm_recs[0]
    for cand in lm_recs:
        if float(cand["step"]) <= step:
            rec = cand
        else:
            break
    link = rec.get("worst_link")
    if not isinstance(link, str) or not link:
        return None, None
    x = rec.get("worst_over_median_x")
    return link, (float(x) if isinstance(x, (int, float))
                  and not isinstance(x, bool) else None)


def straggler_rows(records_by_rank: Mapping[int, List[dict]],
                   kind: Optional[str] = None,
                   monitor: Optional[AnomalyMonitor] = None
                   ) -> Tuple[List[dict], List[dict]]:
    """Per-step slowest-rank attribution + persistence classification.

    Returns (rows, events). Each row: which rank arrived last at that
    step's record, its lag behind the median arrival, and whether its
    EWMA lag marks it persistent (the same host every step) or transient
    (GC pause, one slow input batch). ``monitor`` carries the EWMA state
    and the ``straggler_persistent`` rule — pass the trainer's monitor
    (halt_on set) to make a persistent straggler fail fast; the default
    records only. When the slowest rank shipped ``linkmap`` records,
    the row also carries its dominant slow link (``slow_link`` /
    ``slow_link_x``) — the difference between "rank 2 is late" and
    "rank 2 is late and its dcn hop to rank 5 is 4x the fleet median".
    """
    kind = kind or pick_straggler_kind(records_by_rank)
    if kind is None:
        return [], []
    # The slowest rank's LOCAL critical stage (from its critpath record
    # at that step, when it shipped one): why that host was late, not
    # just that it was.
    crit_idx = _index_by_step(records_by_rank, ("critpath",))
    # And its dominant badput category (from its cumulative ``goodput``
    # records, when it shipped any): the decomposition's verdict on
    # WHERE that host's lost time goes — wait vs wasted vs ckpt — which
    # is the column ``report goodput --advise`` reasons from.
    gp_idx = _goodput_by_rank(records_by_rank)
    # And its dominant slow link (from its ``linkmap`` weather-map
    # records, when it shipped any): WHICH hop is dragging that host.
    lm_idx = _linkmap_by_rank(records_by_rank)
    by_step = _arrival_times(records_by_rank, kind)
    steps = sorted(by_step)
    med_arrivals = [_median(list(by_step[s].values())) for s in steps]
    diffs = sorted(b - a for a, b in zip(med_arrivals, med_arrivals[1:]))
    step_dur = diffs[len(diffs) // 2] if diffs else None

    monitor = monitor or AnomalyMonitor()
    rows: List[dict] = []
    for step in steps:
        times = by_step[step]
        if len(times) < 2:
            continue
        med = _median(list(times.values()))
        lags = {r: t - min(times.values()) for r, t in times.items()}
        slowest = max(times, key=times.get)
        events_before = len(monitor.events)
        monitor.observe_ranks(step, lags, step_dur=step_dur)
        fired = monitor.events[events_before:]
        crec = crit_idx.get(("critpath", step), {}).get(slowest) or {}
        badput, badput_frac = _badput_at(gp_idx.get(slowest), step)
        slow_link, slow_link_x = _slow_link_at(lm_idx.get(slowest), step)
        rows.append({
            "src": kind, "step": step, "field": "straggler",
            "n_ranks": len(times),
            "slowest_rank": slowest,
            "behind_median_s": times[slowest] - med,
            "lag_s": lags[slowest],
            "ewma_lag_s": monitor.rank_lag_ewma.get(slowest, 0.0),
            "persistent": any(ev["rule"] == "straggler_persistent"
                              for ev in fired),
            "stage": crec.get("crit_stage"),
            "badput": badput,
            "badput_frac": badput_frac,
            "slow_link": slow_link,
            "slow_link_x": slow_link_x,
        })
    return rows, list(monitor.events)


def critpath_rows(records_by_rank: Mapping[int, List[dict]],
                  monitor: Optional[AnomalyMonitor] = None
                  ) -> Tuple[List[dict], Dict[int, Dict[str, float]]]:
    """The global critical path: join per-rank ``critpath`` stage-
    interval records by step and run obs/critpath.py's deterministic
    chain walk over each step's segment sets.

    Returns (rows, budgets). Each row: the step's crit_rank/crit_stage,
    ``crit_frac`` (how much of the step wall the chain explains), the
    (rank, stage) chain itself, and per-rank blocked (wait) time.
    ``budgets`` accumulates across steps: per rank, µs ON the chain by
    stage plus total ``blocked_us`` — the eviction-decision view (which
    host binds the fleet, and with which stage). ``monitor`` carries the
    ``critpath_shift`` modal-stage state; pass the trainer's monitor
    (halt_on set) to make a moved bottleneck fail fast."""
    idx = _index_by_step(records_by_rank, ("critpath",))
    monitor = monitor or AnomalyMonitor()
    rows: List[dict] = []
    budgets: Dict[int, Dict[str, float]] = {}
    for (_, step), per_rank in sorted(idx.items()):
        segs_by_rank = {
            r: rec.get("segments") or [] for r, rec in per_rank.items()}
        res = _critpath.critical_path(segs_by_rank)
        events_before = len(monitor.events)
        monitor.observe_critpath(step, crit_stage=res["crit_stage"])
        fired = monitor.events[events_before:]
        rows.append({
            "src": "critpath", "step": step, "field": "critpath",
            "n_ranks": len(per_rank),
            "crit_rank": res["crit_rank"],
            "crit_stage": res["crit_stage"],
            "crit_frac": res["crit_frac"],
            "wall_us": res["wall_us"],
            "chain": res["chain"],
            "stage_us": res["stage_us"],
            "blocked_us": {f"r{r}": us
                           for r, us in res["blocked_us"].items()},
            "shift": any(ev["rule"] == "critpath_shift" for ev in fired),
        })
        for p in res["chain"]:
            b = budgets.setdefault(
                p["rank"], {s: 0.0 for s in _critpath.STAGES})
            b[p["stage"]] += p["t1_us"] - p["t0_us"]
        for r, us in res["blocked_us"].items():
            b = budgets.setdefault(r, {s: 0.0 for s in _critpath.STAGES})
            b["blocked_us"] = b.get("blocked_us", 0.0) + us
    for b in budgets.values():
        for key in list(b):
            b[key] = round(b[key], 1)
    return rows, budgets


def merge(targets: Sequence[str],
          kinds: Sequence[str] = DEFAULT_KINDS,
          straggler_kind: Optional[str] = None,
          monitor: Optional[AnomalyMonitor] = None,
          allow_mismatch: bool = False) -> Dict[str, Any]:
    """One-call fleet merge: resolve + load + validate shards, build the
    merged stat rows, the straggler attribution and the critical-path
    join. Raises on unreadable targets, duplicate ranks, and config_hash
    mismatch (see validate_shards); AnomalyHalt propagates when
    ``monitor`` has ``halt_on`` set and a persistent straggler (or a
    critical-stage shift) fires."""
    shards = resolve_targets(targets)
    records_by_rank, bad = load_shards(shards)
    manifest = validate_shards(records_by_rank,
                               allow_mismatch=allow_mismatch)
    rows = fleet_rows(records_by_rank, kinds=kinds)
    # One monitor carries both rules' state so merge()'s events list is
    # the single ordered stream --obs-halt-on acts on.
    monitor = monitor or AnomalyMonitor()
    stragglers, _ = straggler_rows(
        records_by_rank, kind=straggler_kind, monitor=monitor)
    crit_rows, crit_budget = critpath_rows(records_by_rank,
                                           monitor=monitor)
    gp_rows, gp_by_rank, gp_fleet = goodput_rows(records_by_rank)
    # Forecast plane: the last forecast record any rank shipped (rank 0
    # in practice — the StepForecaster is fed from each rank's own
    # budgets, and the per-P grid is rank-agnostic). None pre-forecast.
    forecast = None
    for rank in sorted(records_by_rank):
        for rec in records_by_rank[rank]:
            if rec.get("kind") == "forecast":
                forecast = rec
    return {
        "shards": {r: shards[r] for r in sorted(shards)},
        "ranks": sorted(shards),
        "n_malformed": bad,
        "manifest": manifest,
        "rows": rows,
        "stragglers": stragglers,
        "critpath": crit_rows,
        "critpath_budget": crit_budget,
        "goodput": gp_rows,
        "goodput_by_rank": gp_by_rank,
        "goodput_fleet": gp_fleet,
        "forecast": forecast,
        "events": list(monitor.events),
    }


def row_record(row: dict) -> dict:
    """A merged row as MetricsLogger-loggable fields (kind="fleet"):
    drops nothing — the skew dict is JSON-native — but guards against
    key collisions with the logger's own meta fields."""
    return {k: v for k, v in row.items() if k not in ("kind", "time",
                                                      "rank")}


def fleet_shard_name(rank: int) -> str:
    """Re-export so callers needing the naming contract import one
    module (the merger) rather than reaching into utils."""
    return shard_filename(rank)
