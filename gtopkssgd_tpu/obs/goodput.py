"""Goodput ledger: end-to-end productive-time accounting per rank.

The paper's whole argument is wall-clock economics (arXiv:1901.04359:
on slow networks most of a synchronous step is NOT productive compute),
and the repo measures every plane in isolation — attr/critpath time,
compile/memory space, the calibrated comm model, recovery actions — but
had no single number for "what fraction of this run's wall-clock
actually advanced training?". This module is that instrument: a
per-rank partition of the run's measured wall into **goodput**
(productive step compute) and a closed **badput taxonomy**:

  category  what it accounts
  --------  ----------------------------------------------------------
  goodput   productive step compute (the compute share of each step)
  select    sparsification overhead: top-k selection + wire codec
  comm      wire time of the sparse exchange (the bytes themselves)
  wait      blocked at collectives for skewed peers + injected slowness
  compile   XLA lower/compile time (startup AOT pass and recompiles)
  ckpt      checkpoint save/restore (incl. emergency preemption saves
            and rollback restores)
  wasted    re-executed work: steps discarded by skip/rollback recovery
  degraded  degraded-mode delta: extra step time while the dense
            fallback replaces the sparse step
  data      input-pipeline stalls (host batch assembly the step waited
            on)
  startup   init: process start to the first training step (minus any
            time already attributed, e.g. the AOT compile)
  other     the explicit unattributed remainder — NEVER hidden

The hard invariant that makes this a real instrument rather than a
dashboard is **conservation**: the categories plus ``other`` sum to the
measured wall by construction (``other = wall - sum(categories)``), so
nothing can be silently double-counted or dropped — a large
``other_frac`` is a visible accounting gap, and the gate smoke pins it
small (<= 0.05) on the clean arm.

Two producers, one record shape:

  ``GoodputLedger``  the live accumulator the trainer drives at its
      existing sync points via a cursor API (``mark``/``step_mark``):
      each call attributes the wall-clock since the previous mark to
      one category; step time is split goodput/select/comm/wait by the
      latest critpath stage fractions (without critpath the whole step
      counts as goodput — conservative toward goodput, documented).
      Every ``interval`` steps it logs one durable cumulative
      ``goodput`` record (fsync'd) and feeds ``goodput_frac`` to the
      anomaly monitor's ``goodput_collapse`` rule; ``__exit__`` logs
      the end-of-run summary (``final=1``).

  ``fold_shards``  the offline fold for runs (or fixture shards) — the
      last cumulative ``goodput`` record per rank wins; ranks that
      shipped none get a best-effort synthesis from the records the run
      already emits (manifest/step timing for wall+startup, critpath
      stage fractions for the step split, ``compile`` records,
      ``recovery`` skip/rollback counts), tagged ``source="folded"``.

``report goodput`` renders the decomposition (per-rank bars,
chaos-vs-clean compare); ``--advise`` turns it into the ROADMAP item-1
eviction hint: the rank whose badput drags furthest below the fleet
median, and what evicting it would recover.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence

GOODPUT = "goodput"

# Badput taxonomy, in tie-break order (dominant_badput prefers earlier
# entries on ties — real work overheads before skew before bookkeeping).
BADPUT = ("select", "comm", "wait", "compile", "ckpt", "wasted",
          "degraded", "data", "startup")

# Every accounted category; ``other`` is derived, never accumulated.
CATEGORIES = (GOODPUT,) + BADPUT

_EPS = 1e-9


def _finite(x: Any) -> bool:
    return (isinstance(x, (int, float)) and not isinstance(x, bool)
            and math.isfinite(x))


# --------------------------------------------------------------- records

def decomposition(seconds: Mapping[str, float], wall_s: float,
                  step: Optional[int] = None,
                  n_wasted_steps: int = 0,
                  final: bool = False,
                  source: str = "ledger") -> Dict[str, Any]:
    """The flat cumulative ``goodput`` record (no 'kind' key — callers
    log it as kind="goodput"). Conservation by construction:
    ``other_s = wall_s - sum(categories)`` — a negative ``other_s``
    (only possible via caller double-counting) is surfaced, not
    clamped, so the conservation tests can see it."""
    wall = float(wall_s)
    rec: Dict[str, Any] = {} if step is None else {"step": int(step)}
    total = 0.0
    for cat in CATEGORIES:
        s = float(seconds.get(cat, 0.0))
        total += s
        rec[f"{cat}_s"] = round(s, 6)
    other = wall - total
    rec["wall_s"] = round(wall, 6)
    rec["other_s"] = round(other, 6)
    rec["goodput_frac"] = (round(float(seconds.get(GOODPUT, 0.0)) / wall, 6)
                           if wall > _EPS else 0.0)
    rec["other_frac"] = round(other / wall, 6) if wall > _EPS else 0.0
    rec["n_wasted_steps"] = int(n_wasted_steps)
    rec["final"] = int(bool(final))
    rec["source"] = source
    return rec


def conservation_error(rec: Mapping[str, Any]) -> float:
    """|wall - (categories + other)| / wall — zero (to rounding) for
    any record built by ``decomposition``; the gate smoke pins it."""
    wall = float(rec.get("wall_s", 0.0))
    if wall <= _EPS:
        return 0.0
    total = sum(float(rec.get(f"{c}_s", 0.0)) for c in CATEGORIES)
    total += float(rec.get("other_s", 0.0))
    return abs(wall - total) / wall


def category_fracs(rec: Mapping[str, Any]) -> Dict[str, float]:
    """{category: share of wall}, ``other`` included."""
    wall = float(rec.get("wall_s", 0.0))
    if wall <= _EPS:
        return {c: 0.0 for c in CATEGORIES + ("other",)}
    return {c: float(rec.get(f"{c}_s", 0.0)) / wall
            for c in CATEGORIES + ("other",)}


def dominant_badput(rec: Mapping[str, Any]) -> Optional[str]:
    """Largest badput category by seconds; BADPUT order breaks ties;
    None when no badput was accounted at all (``other`` is an
    accounting gap, not a diagnosis, so it never wins)."""
    best, best_s = None, 0.0
    for cat in BADPUT:
        s = float(rec.get(f"{cat}_s", 0.0))
        if s > best_s + _EPS:
            best, best_s = cat, s
    return best


# ---------------------------------------------------------- live ledger

class GoodputLedger:
    """Cursor-based live accumulator. Every ``mark(category)`` call
    attributes the wall-clock elapsed since the previous mark to one
    category and advances the cursor; ``mark(None)`` advances without
    attributing (the dropped span lands in ``other`` — the honest
    choice for phases the taxonomy genuinely does not cover, e.g.
    host-side trace attribution; eval is productive and accrues to
    goodput). Because each instant is attributed at most once,
    conservation holds by construction.

    ``metrics``/``monitor`` are the trainer's MetricsLogger and
    AnomalyMonitor (either may be None for in-memory use); ``interval``
    is the durable-record cadence in optimizer steps (<= 0 disables
    periodic logging — the end-of-run summary still lands)."""

    def __init__(self, metrics=None, monitor=None, interval: int = 50,
                 clock=time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._cursor = self._t0
        self.metrics = metrics
        self.monitor = monitor
        self.interval = int(interval)
        self.seconds: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self.n_wasted_steps = 0
        self._started = False
        self._fracs: Optional[Dict[str, float]] = None
        # Current-step attribution (so skip/rollback can reclassify the
        # just-executed step as wasted) + the clean-step EWMA the
        # degraded-mode delta is measured against.
        self._cur_step: Dict[str, float] = {}
        self._cur_degraded = False
        self._step_ewma: Optional[float] = None
        self._last_logged: Optional[int] = None

    # ------------------------------------------------------------ cursor
    def mark(self, category: Optional[str]) -> float:
        """Attribute the span since the last mark to ``category`` (one
        of CATEGORIES) and advance the cursor; None drops the span into
        the unattributed remainder. Returns the span in seconds."""
        now = self._clock()
        dt = max(0.0, now - self._cursor)
        self._cursor = now
        if category is not None and dt > 0.0:
            if category not in self.seconds:
                raise ValueError(
                    f"unknown goodput category {category!r} "
                    f"(registered: {CATEGORIES})")
            self.seconds[category] += dt
        return dt

    def train_started(self) -> None:
        """First training step is imminent: everything since
        construction not already attributed (e.g. the AOT compile) is
        startup/init. Subsequent calls (fit() re-entering train())
        drop the inter-call span into ``other`` — eval and epoch
        bookkeeping are not startup."""
        if self._started:
            self.mark(None)
            return
        self._started = True
        self.mark("startup")

    def note_stage_fracs(self, critpath_rec: Mapping[str, Any]) -> None:
        """Adopt the latest critpath record's stage shares as the step
        split: compute->goodput, select/comm/wait->their categories.
        Fractions are normalized over the record's own stage totals so
        they always sum to 1 regardless of profiler gaps."""
        tot = {
            GOODPUT: float(critpath_rec.get("t_compute_us", 0.0)),
            "select": float(critpath_rec.get("t_select_us", 0.0)),
            "comm": float(critpath_rec.get("t_comm_wire_us", 0.0)),
            "wait": float(critpath_rec.get("t_wait_us", 0.0)),
        }
        total = sum(tot.values())
        if total <= _EPS:
            return
        self._fracs = {c: v / total for c, v in tot.items()}

    def step_mark(self, begin: bool = False,
                  degraded: bool = False) -> float:
        """Attribute the span since the last mark as step time, split
        by the adopted critpath stage fractions (all goodput when no
        critpath plane is on). ``begin=True`` closes the PREVIOUS
        step's accumulation first (feeding the clean-step EWMA) —
        call it for the dispatch span, then plain ``step_mark()`` for
        the post-step sync reads of the same iteration. While
        ``degraded``, the span's excess over the clean-step EWMA is
        badput (``degraded``), the remainder splits normally."""
        if begin:
            self._close_step()
            self._cur_degraded = False
        dt = self.mark(None)  # cursor advanced; attribute manually below
        if dt <= 0.0:
            return dt
        span = dt
        if degraded:
            self._cur_degraded = True
            if self._step_ewma is not None and span > self._step_ewma:
                extra = span - self._step_ewma
                self.seconds["degraded"] += extra
                self._cur_step["degraded"] = (
                    self._cur_step.get("degraded", 0.0) + extra)
                span = self._step_ewma
        fracs = self._fracs or {GOODPUT: 1.0}
        for cat, f in fracs.items():
            s = span * f
            self.seconds[cat] += s
            self._cur_step[cat] = self._cur_step.get(cat, 0.0) + s
        return dt

    def _close_step(self) -> None:
        if self._cur_step and not self._cur_degraded:
            total = sum(self._cur_step.values())
            a = 0.3
            self._step_ewma = (total if self._step_ewma is None
                               else self._step_ewma
                               + a * (total - self._step_ewma))
        self._cur_step = {}

    def wasted_step(self) -> float:
        """Reclassify the current step's accumulated attribution as
        ``wasted`` — a skip discarded exactly this step's update, a
        rollback discards it and more (the additional rewound progress
        stays where it was honestly spent; only the re-execution to
        come re-earns it). Returns the reclassified seconds."""
        total = 0.0
        for cat, s in self._cur_step.items():
            self.seconds[cat] -= s
            total += s
        if total > 0.0:
            self.seconds["wasted"] += total
        self.n_wasted_steps += 1
        self._cur_step = {}
        return total

    # ----------------------------------------------------------- records
    def wall_s(self) -> float:
        return self._clock() - self._t0

    def snapshot(self, step: int, final: bool = False) -> Dict[str, Any]:
        return decomposition(self.seconds, self.wall_s(), step=step,
                             n_wasted_steps=self.n_wasted_steps,
                             final=final)

    def log_record(self, step: int, final: bool = False) -> Dict[str, Any]:
        """One durable cumulative record (fsync'd — the summary must
        survive a kill one line later) + the monitor feed. AnomalyHalt
        from goodput_collapse propagates AFTER the record is durable,
        like every other monitor halt; the final summary never feeds
        the monitor (the run is already ending)."""
        rec = self.snapshot(step, final=final)
        if self.metrics is not None:
            self.metrics.log("goodput", flush=True, **rec)
        if self.monitor is not None and not final:
            self.monitor.observe_goodput(
                step, goodput_frac=rec["goodput_frac"])
        return rec

    def tick(self, step: int) -> Optional[Dict[str, Any]]:
        """Periodic-record gate for the trainer's sync points: logs one
        cumulative record when ``interval`` steps have passed since the
        last one. The FIRST tick only arms the cadence (short default
        runs stay record-free until the end-of-run summary)."""
        if self.interval <= 0:
            return None
        if self._last_logged is None:
            self._last_logged = int(step)
            return None
        if step - self._last_logged < self.interval:
            return None
        self._last_logged = int(step)
        return self.log_record(step)


# --------------------------------------------------------- offline fold

def _mean_stage_fracs(records: Sequence[Mapping[str, Any]]
                      ) -> Optional[Dict[str, float]]:
    sums = {GOODPUT: 0.0, "select": 0.0, "comm": 0.0, "wait": 0.0}
    n = 0
    for rec in records:
        if rec.get("kind") != "critpath":
            continue
        tot = {
            GOODPUT: float(rec.get("t_compute_us", 0.0)),
            "select": float(rec.get("t_select_us", 0.0)),
            "comm": float(rec.get("t_comm_wire_us", 0.0)),
            "wait": float(rec.get("t_wait_us", 0.0)),
        }
        total = sum(tot.values())
        if total <= _EPS:
            continue
        for c in sums:
            sums[c] += tot[c] / total
        n += 1
    if n == 0:
        return None
    return {c: v / n for c, v in sums.items()}


def synthesize(records: Sequence[Mapping[str, Any]]
               ) -> Optional[Dict[str, Any]]:
    """Best-effort decomposition for a record stream WITHOUT live
    ``goodput`` records, from evidence the run already emits: wall and
    startup from manifest/step-record timing, the step split from mean
    critpath stage fractions (all goodput without critpath), compile
    seconds from ``compile`` records, wasted steps from ``recovery``
    skip/rollback actions priced at the median step duration. An
    estimate — tagged ``source="folded"`` — with everything it could
    not see left in ``other``. None when the stream has no timed step
    records at all."""
    manifest_t: Optional[float] = None
    step_times: Dict[float, float] = {}
    compile_s = 0.0
    wasted_actions = 0
    last_step = 0
    for rec in records:
        kind = rec.get("kind")
        if kind == "manifest" and manifest_t is None:
            if _finite(rec.get("time")):
                manifest_t = float(rec["time"])
        elif kind in ("obs", "train"):
            if _finite(rec.get("step")) and _finite(rec.get("time")):
                s = float(rec["step"])
                step_times[s] = max(step_times.get(s, 0.0),
                                    float(rec["time"]))
                last_step = max(last_step, int(s))
        elif kind == "compile":
            for field in ("lower_s", "compile_s"):
                if _finite(rec.get(field)):
                    compile_s += float(rec[field])
        elif kind == "recovery" and rec.get("action") in ("skip",
                                                          "rollback"):
            wasted_actions += 1
    if not step_times:
        return None
    order = sorted(step_times)
    t_first, t_last = step_times[order[0]], step_times[order[-1]]
    diffs = sorted(b - a for a, b in zip(
        [step_times[s] for s in order],
        [step_times[s] for s in order[1:]]))
    step_dur = diffs[len(diffs) // 2] if diffs else 0.0
    t0 = manifest_t if manifest_t is not None else t_first
    wall = max(0.0, t_last - t0)
    seconds = {c: 0.0 for c in CATEGORIES}
    # Startup: manifest to first step record, minus that first step's
    # own duration (estimated at the median cadence).
    seconds["startup"] = max(0.0, t_first - t0 - step_dur)
    seconds["compile"] = min(compile_s, seconds["startup"])
    seconds["startup"] -= seconds["compile"]
    seconds["wasted"] = wasted_actions * step_dur
    stepped = max(0.0, wall - seconds["startup"] - seconds["compile"]
                  - seconds["wasted"])
    fracs = _mean_stage_fracs(records) or {GOODPUT: 1.0}
    for cat, f in fracs.items():
        seconds[cat] += stepped * f
    return decomposition(seconds, wall, step=last_step,
                         n_wasted_steps=wasted_actions, final=True,
                         source="folded")


def fold(records: Sequence[Mapping[str, Any]]
         ) -> Optional[Dict[str, Any]]:
    """One rank's decomposition: the LAST cumulative ``goodput`` record
    wins (the ledger's records are cumulative, so the last one IS the
    run's accounting); streams without any fall back to
    ``synthesize``."""
    last = None
    for rec in records:
        if rec.get("kind") == "goodput":
            last = rec
    if last is not None:
        out = {k: v for k, v in last.items()
               if k not in ("kind", "time", "rank")}
        out.setdefault("source", "ledger")
        return out
    return synthesize(records)


def fold_shards(records_by_rank: Mapping[int, Sequence[Mapping[str, Any]]]
                ) -> Dict[int, Dict[str, Any]]:
    """{rank: decomposition} over fleet shards; ranks whose streams
    yield nothing (no goodput records AND nothing to synthesize from)
    are absent, never invented."""
    out: Dict[int, Dict[str, Any]] = {}
    for rank in sorted(records_by_rank):
        d = fold(records_by_rank[rank])
        if d is not None:
            out[rank] = d
    return out


def fleet_decomposition(decomp_by_rank: Mapping[int, Mapping[str, Any]]
                        ) -> Optional[Dict[str, Any]]:
    """Whole-fleet decomposition: wall-weighted sum of the per-rank
    category seconds (a rank-second is a rank-second — the fleet's
    goodput_frac is total productive rank-time over total rank-time)."""
    if not decomp_by_rank:
        return None
    seconds = {c: 0.0 for c in CATEGORIES}
    wall = 0.0
    wasted = 0
    for d in decomp_by_rank.values():
        wall += float(d.get("wall_s", 0.0))
        wasted += int(d.get("n_wasted_steps", 0) or 0)
        for c in CATEGORIES:
            seconds[c] += float(d.get(f"{c}_s", 0.0))
    rec = decomposition(seconds, wall, n_wasted_steps=wasted, final=True,
                        source="fleet")
    rec["n_ranks"] = len(decomp_by_rank)
    return rec


# ------------------------------------------------------- advise / render

def advise(decomp_by_rank: Mapping[int, Mapping[str, Any]],
           margin: float = 0.1) -> Optional[Dict[str, Any]]:
    """The ROADMAP item-1 eviction hint: the rank whose goodput_frac
    sits furthest below the fleet median by more than ``margin``
    (absolute), with its dominant badput category — the difference
    between "evict rank 2" and "rank 2 spends 48% of its wall blocked
    at collectives; evicting or replacing it recovers ~X s of fleet
    time". None when no rank stands out (a healthy fleet gets no
    advice) or the fleet has < 2 ranks (nothing to evict INTO)."""
    if len(decomp_by_rank) < 2:
        return None
    fracs = {r: float(d.get("goodput_frac", 0.0))
             for r, d in decomp_by_rank.items()}
    med = sorted(fracs.values())[len(fracs) // 2] if len(fracs) % 2 else \
        0.5 * sum(sorted(fracs.values())[len(fracs) // 2 - 1:
                                         len(fracs) // 2 + 1])
    worst = min(sorted(fracs), key=lambda r: fracs[r])
    if med - fracs[worst] <= margin:
        return None
    d = decomp_by_rank[worst]
    cat = dominant_badput(d)
    lost = (med - fracs[worst]) * float(d.get("wall_s", 0.0))
    return {
        "rank": worst,
        "goodput_frac": round(fracs[worst], 6),
        "fleet_median_frac": round(med, 6),
        "dominant_badput": cat,
        "recoverable_s": round(lost, 6),
    }


def _bar(frac: float, width: int = 20) -> str:
    n = int(round(max(0.0, min(1.0, frac)) * width))
    return "#" * n + "." * (width - n)


def format_goodput(decomp_by_rank: Mapping[int, Mapping[str, Any]],
                   fleet: Optional[Mapping[str, Any]] = None,
                   compare: Optional[Mapping[int, Mapping[str, Any]]]
                   = None,
                   hint: Optional[Mapping[str, Any]] = None) -> str:
    """Render the decomposition the way ``report goodput`` prints it:
    per-rank category table + goodput bars, the whole-fleet line, an
    optional clean-vs-chaos compare (per-category frac deltas against a
    second run's fleet decomposition) and the ``--advise`` hint."""
    cols = ["rank", "wall_s", GOODPUT] + list(BADPUT) + ["other", "src"]
    lines: List[str] = []
    table: List[List[str]] = []
    for rank in sorted(decomp_by_rank):
        d = decomp_by_rank[rank]
        fr = category_fracs(d)
        table.append(
            [f"r{rank}", f"{float(d.get('wall_s', 0.0)):.3f}"]
            + [f"{fr[c]:.4f}" for c in (GOODPUT,) + BADPUT + ("other",)]
            + [str(d.get("source", "?"))])
    if table:
        widths = [max(len(x[i]) for x in [cols] + table)
                  for i in range(len(cols))]
        lines.append("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
        lines.append("  ".join("-" * w for w in widths))
        for x in table:
            lines.append("  ".join(c.ljust(w) for c, w in zip(x, widths)))
        lines.append("")
        for rank in sorted(decomp_by_rank):
            d = decomp_by_rank[rank]
            gf = float(d.get("goodput_frac", 0.0))
            bad = dominant_badput(d)
            lines.append(f"r{rank} goodput [{_bar(gf)}] {gf:.1%}"
                         + (f"  worst badput: {bad}" if bad else ""))
    else:
        lines.append("(no goodput decomposition — no goodput records "
                     "and nothing to synthesize from)")
    if fleet is not None:
        lines.append("")
        lines.append(
            f"fleet ({fleet.get('n_ranks', '?')} ranks): goodput "
            f"{float(fleet.get('goodput_frac', 0.0)):.1%} of "
            f"{float(fleet.get('wall_s', 0.0)):.3f} rank-seconds, "
            f"other {float(fleet.get('other_frac', 0.0)):.1%}, "
            f"{int(fleet.get('n_wasted_steps', 0) or 0)} wasted steps")
    if compare is not None:
        ours = fleet or fleet_decomposition(decomp_by_rank)
        theirs = fleet_decomposition(compare)
        if ours is not None and theirs is not None:
            lines.append("")
            lines.append("vs compare run (this - other, share of wall):")
            fa, fb = category_fracs(ours), category_fracs(theirs)
            for c in (GOODPUT,) + BADPUT + ("other",):
                d = fa[c] - fb[c]
                if abs(d) >= 0.0005:
                    lines.append(f"  {c:<9} {fa[c]:>7.4f} vs {fb[c]:>7.4f}"
                                 f"  ({d:+.4f})")
    if hint is not None:
        lines.append("")
        lines.append(
            f"advise: evict/replace rank {hint['rank']} — goodput "
            f"{float(hint['goodput_frac']):.1%} vs fleet median "
            f"{float(hint['fleet_median_frac']):.1%}, dominant badput "
            f"{hint['dominant_badput']}; recovers "
            f"~{float(hint['recoverable_s']):.1f} rank-seconds")
    elif hint is None and compare is None and len(decomp_by_rank) >= 2:
        pass
    return "\n".join(lines)
