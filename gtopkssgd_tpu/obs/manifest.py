"""Run manifest: the self-describing header of every metrics.jsonl.

A metrics file divorced from the flags, code revision, and hardware that
produced it is archaeology, not observability — round 5's BENCH triage
spent most of its time reconstructing exactly that context from shell
history. The manifest is ONE extra jsonl record (kind "manifest", written
first) stamping the run with a config hash, the resolved headline flags,
the mesh shape, jax/backend versions, and the git sha, so
``obs.report`` can display provenance and the ``report gate`` can refuse
to compare runs whose configs differ.

Everything here is host-side and dependency-free (stdlib + an
already-initialized jax); git is optional (sha is null outside a
checkout or if git is missing).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
from typing import Any, Dict, Optional

# Resolved-config fields surfaced as first-class manifest keys (the rest
# of the config is captured by the hash). Order is display order.
_HEADLINE_KEYS = (
    "dnn",
    "dataset",
    "compression",
    "density",
    "wire_codec",
    "nworkers",
    "batch_size",
    "seed",
)


def _config_dict(config: Any) -> Dict[str, Any]:
    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        return dataclasses.asdict(config)
    return dict(config)


def config_hash(config: Any) -> str:
    """Stable short hash of the FULL config (sorted-key json; non-json
    leaves stringified), so two runs are comparable iff their hashes
    match — headline fields alone under-determine a run."""
    blob = json.dumps(_config_dict(config), sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def git_sha(repo_dir: Optional[str] = None) -> Optional[str]:
    """Short sha of the working tree this process imported from; None
    when git/the checkout is unavailable (installed package, CI tarball).
    '-dirty' is appended when tracked files have uncommitted changes, so
    a sha in a manifest is only trustworthy when clean."""
    repo_dir = repo_dir or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=repo_dir, capture_output=True, text=True, timeout=5)
        if out.returncode != 0 or not out.stdout.strip():
            return None
        sha = out.stdout.strip()
        dirty = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            cwd=repo_dir, capture_output=True, text=True, timeout=5)
        if dirty.returncode == 0 and dirty.stdout.strip():
            sha += "-dirty"
        return sha
    except Exception:
        return None


def coordinator_address() -> Optional[str]:
    """The jax.distributed coordinator this process joined, or None for
    single-process runs. Read from jax's internal distributed state —
    there is no public accessor — so failures of any shape degrade to
    None rather than killing the run for a header field."""
    try:
        from jax._src import distributed

        return distributed.global_state.coordinator_address
    except Exception:
        return None


def run_manifest(config: Any = None, mesh=None, **extra) -> Dict[str, Any]:
    """Assemble the manifest record body (no "kind"/"time" — the metrics
    logger adds those). ``config`` is any dataclass or mapping;
    ``mesh`` a jax Mesh (axis names -> sizes); ``extra`` lands verbatim
    (e.g. num_params, steps_per_epoch). Requires jax to already be
    initialized in the intended configuration — the backend fields
    record what THIS process actually ran on."""
    import jax

    man: Dict[str, Any] = {}
    if config is not None:
        cfg = _config_dict(config)
        man["config_hash"] = config_hash(cfg)
        for key in _HEADLINE_KEYS:
            if key in cfg:
                man[key] = cfg[key]
    if mesh is not None:
        man["mesh_shape"] = {
            str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        }
    man["jax_version"] = jax.__version__
    try:
        import jaxlib

        man["jaxlib_version"] = jaxlib.__version__
    except Exception:
        pass
    try:
        man["backend"] = jax.default_backend()
        man["device_kind"] = jax.devices()[0].device_kind
        man["device_count"] = jax.device_count()
        man["process_count"] = jax.process_count()
        # WHICH process wrote this shard — with process_count and the
        # coordinator address, the fleet merger can confirm that shards
        # in one dir really are one distributed run (config_hash is the
        # primary join key; these make mismatch errors explainable).
        man["process_index"] = jax.process_index()
    except Exception:
        # A dead accelerator tunnel must not kill the run for a header.
        man.setdefault("backend", None)
    man["coordinator_address"] = coordinator_address()
    man["git_sha"] = git_sha()
    man.update(extra)
    return man
