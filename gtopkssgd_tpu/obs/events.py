"""Online anomaly events: the run notices its own failures in flight.

arXiv:1911.08772 ties top-k-with-error-feedback convergence to the
residual dynamics; PRs 1–2 made those dynamics (and the achieved wire
density) per-step telemetry, but nothing LOOKED at the stream — a NaN
loss, a collapsed density, or a runaway residual was discovered by a
human reading metrics.jsonl after the fact. ``AnomalyMonitor`` closes the
loop inside the train loop, at the cadence the telemetry is already
synced (no extra device reads):

  rule                  severity  fires when
  --------------------  --------  -------------------------------------
  nan_loss              error     loss is NaN/Inf
  loss_spike            warn      EWMA z-score of the loss exceeds
                                  ``loss_spike_z`` (after warmup)
  density_collapse      warn      achieved_density < collapse_frac * rho
                                  (sparse modes; selection went degenerate)
  residual_blowup       warn      residual_norm > blowup_x * its EWMA
                                  (error feedback diverging, after warmup)
  residual_age_runaway  warn      max per-layer mean residual age >
                                  age_max steps (starved coordinates;
                                  auto threshold 100/rho — uniform
                                  rotation re-ships a coordinate every
                                  ~1/rho steps)
  straggler_persistent  warn      a rank's EWMA sync-point lag exceeds
                                  straggler_lag_s (auto: straggler_lag_x
                                  x the observed step duration) after
                                  straggler_warmup merged steps — the
                                  same host is late EVERY step, not a
                                  one-off GC pause. Fed by the fleet
                                  merger (obs/fleet.py) through
                                  ``observe_ranks``, so --obs-halt-on
                                  covers it like any other rule
  comm_model_drift      warn      the live calibrator's alpha/beta fit
                                  (obs/calib.py) diverges from the
                                  planner's committed inputs by more
                                  than ``comm_drift_x`` in either
                                  direction, after ``comm_drift_warmup``
                                  prior refits — the comm model that
                                  priced the schedule/bucketing no
                                  longer describes the fabric. Fed by
                                  CommCalibrator.refit through
                                  ``observe_comm_model``
  recompile_storm       warn      the jitted step's executable cache
                                  grew after ``recompile_warmup`` prior
                                  polls — a drifting dispatch shape is
                                  retracing the hot step every few
                                  dispatches. Fed by obs/memwatch.py's
                                  CompileWatch through
                                  ``observe_compile``
  device_mem_leak       warn      sampled live-array bytes grew across
                                  ``mem_leak_windows`` CONSECUTIVE
                                  windows (a plateau resets the streak;
                                  fires once per monotonic run). Fed by
                                  the live-memory watch (obs/memwatch)
                                  through ``observe_memory``
  hbm_headroom          warn      device bytes_in_use crossed
                                  ``hbm_headroom_frac`` of bytes_limit
                                  (fires on the crossing; re-arms when
                                  usage drops back under). Same feed as
                                  device_mem_leak
  critpath_shift        warn      the fleet's global critical stage
                                  (obs/critpath.py via the fleet join)
                                  differed from the established modal
                                  stage for ``critpath_shift_windows``
                                  CONSECUTIVE joined steps — the step's
                                  bottleneck moved (e.g. compute→wait:
                                  a peer started skewing the
                                  collective). Fires once per shift,
                                  then adopts the new stage as modal
                                  and re-arms. Fed by the fleet merger
                                  through ``observe_critpath``
  goodput_collapse      warn      the run's cumulative goodput_frac
                                  (obs/goodput.py ledger) dropped below
                                  ``goodput_collapse_frac`` x its own
                                  EWMA for ``goodput_collapse_windows``
                                  CONSECUTIVE ledger observations —
                                  wall-clock is still passing but it
                                  stopped buying training progress
                                  (storm of waits/recoveries/ckpts).
                                  Fed by the GoodputLedger's periodic
                                  durable records through
                                  ``observe_goodput``
  link_degraded         warn      one link's EWMA latency in the
                                  weather map (obs/linkmap.py) stayed
                                  above ``link_degraded_x`` x the
                                  fleet-median link EWMA for
                                  ``link_degraded_windows`` CONSECUTIVE
                                  observations — a specific (axis,
                                  peer-pair) hop degraded, not just
                                  "some rank is slow". The streak IS
                                  the warmup (no single-window fire);
                                  fires once per streak, then re-arms.
                                  Fed by LinkMap.observe through
                                  ``observe_links`` AFTER the durable
                                  linkmap record is written
  forecast_drift        warn      the forecast plane's hindcast error
                                  (obs/forecast.py: predicted vs
                                  measured step time on THIS run)
                                  stayed beyond ``forecast_drift_x`` for
                                  ``forecast_drift_windows`` CONSECUTIVE
                                  observations — the digital twin no
                                  longer explains the run it was fitted
                                  on, so its P-target recommendations
                                  are not evidence. The streak IS the
                                  warmup; fires once per streak, then
                                  re-arms. Fed by StepForecaster.observe
                                  through ``observe_forecast`` AFTER the
                                  durable forecast record is written

Every rule name is registered in the module-level ``RULES`` frozenset
(the event-plane mirror of ``utils/metrics.KINDS``): ``_emit`` rejects
unregistered names at runtime, graftlint's event-rule check rejects them
statically at emit sites, and a tier-1 doc-drift test pins the README's
event table to exactly this set.

Each firing emits one severity-tagged ``event`` record through
MetricsLogger with ``flush=True`` (fsync'd — a run killed one line later
keeps its diagnosis) and an instant marker on the timeline when one is
recording. ``halt_on`` turns detection into fail-fast: observing an event
at (or above) that severity raises ``AnomalyHalt`` after the record is
durably written, and dist_trainer maps it to exit code 44 (the watchdog
owns 43).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional

# Exit code for --obs-halt-on fail-fast (watchdog stalls exit
# EXIT_STALL). Single source: gtopkssgd_tpu/exit_codes.py, re-exported
# under the historical name every consumer already imports.
from gtopkssgd_tpu.exit_codes import EXIT_ANOMALY_HALT as HALT_EXIT_CODE

_SEVERITY_RANK = {"info": 0, "warn": 1, "error": 2}

# Every rule name the monitor may emit — the event-plane mirror of
# utils/metrics.KINDS. An event record whose "rule" is not here is a
# bug (typo'd emit site, undocumented rule): _emit raises, graftlint's
# event-rule check flags the emit site statically, and the README
# doc-drift test keeps the event table covering exactly this set.
RULES = frozenset({
    "nan_loss",              # non-finite loss (error)
    "loss_spike",            # loss EWMA z-score excursion
    "density_collapse",      # achieved density << configured rho
    "residual_blowup",       # error-feedback residual diverging
    "residual_age_runaway",  # starved coordinates (stale residuals)
    "straggler_persistent",  # one rank late at EVERY sync point
    "comm_model_drift",      # live alpha/beta fit off the planner's
    "recompile_storm",       # executable cache growing on the hot step
    "device_mem_leak",       # live bytes growing monotonically
    "hbm_headroom",          # bytes_in_use near bytes_limit
    "critpath_shift",        # global critical stage moved
    "goodput_collapse",      # goodput_frac fell off its own EWMA
    "link_degraded",         # one (axis, peer) link's EWMA pulled away
                             # from the fleet median (obs/linkmap.py)
    "forecast_drift",        # hindcast error beyond bound — the model
                             # stopped explaining the run (obs/forecast)
})


class AnomalyHalt(RuntimeError):
    """Raised by AnomalyMonitor.observe when an event reaches the
    configured halt severity. Carries the triggering event record."""

    def __init__(self, event: Dict[str, Any]):
        super().__init__(
            f"anomaly halt: {event.get('rule')} "
            f"(severity={event.get('severity')}, step={event.get('step')}, "
            f"value={event.get('value')})")
        self.event = event


@dataclasses.dataclass
class Thresholds:
    """Rule thresholds; defaults documented in the README's event table."""

    loss_spike_z: float = 6.0        # EWMA z-score
    loss_ewma_alpha: float = 0.1     # EWMA decay for loss mean/var
    loss_warmup: int = 5             # observations before spike/blowup arm
    density_collapse_frac: float = 0.1   # achieved < frac * rho
    residual_blowup_x: float = 10.0  # residual_norm vs its EWMA
    residual_age_max: float = 0.0    # steps; 0 = auto (100 / rho)
    straggler_lag_s: float = 0.0     # seconds; 0 = auto (lag_x * step dur)
    straggler_lag_x: float = 2.0     # auto threshold: x * step duration
    straggler_ewma_alpha: float = 0.3    # EWMA decay for per-rank lag
    straggler_warmup: int = 2        # merged steps before the rule arms
    comm_drift_x: float = 4.0        # live fit vs planner inputs, either
                                     # direction (max of a/b and b/a)
    comm_drift_warmup: int = 2       # refits before the drift rule arms
    recompile_warmup: int = 1        # compile-watch polls before
                                     # recompile_storm arms (0 = any
                                     # cache growth fires, even the
                                     # first poll's)
    mem_leak_windows: int = 3        # consecutive growing live-bytes
                                     # windows before device_mem_leak
    hbm_headroom_frac: float = 0.92  # bytes_in_use / bytes_limit above
                                     # which hbm_headroom fires
    critpath_shift_windows: int = 3  # consecutive joined steps whose
                                     # global critical stage differs
                                     # from the modal one before
                                     # critpath_shift fires
    goodput_collapse_windows: int = 3    # consecutive ledger records
                                         # below the drop threshold
                                         # before goodput_collapse fires
    goodput_collapse_frac: float = 0.5   # current goodput_frac < frac *
                                         # its EWMA counts as a drop
    goodput_ewma_alpha: float = 0.3      # EWMA decay for goodput_frac
    goodput_warmup: int = 2          # ledger records before the
                                     # collapse rule arms (early-run
                                     # fractions are startup-dominated)
    link_degraded_x: float = 4.0     # a link's EWMA latency vs the
                                     # fleet-median link EWMA above
                                     # which a window counts as degraded
    link_degraded_windows: int = 3   # consecutive degraded windows
                                     # before link_degraded fires (the
                                     # streak is the rule's warmup —
                                     # one noisy window never fires)
    forecast_drift_x: float = 4.0    # hindcast error factor (predicted
                                     # vs measured step time, either
                                     # direction) above which a window
                                     # counts as drifted
    forecast_drift_windows: int = 3  # consecutive drifted windows
                                     # before forecast_drift fires (the
                                     # streak is the warmup — one noisy
                                     # capture never fires)

    def age_max(self, rho: Optional[float]) -> float:
        if self.residual_age_max > 0:
            return self.residual_age_max
        if rho and rho > 0:
            return 100.0 / rho
        return math.inf

    def straggler_threshold(self, step_dur: Optional[float]) -> float:
        """Seconds of EWMA lag that makes a rank a persistent straggler.
        Explicit straggler_lag_s wins; otherwise auto-scale to the run's
        own cadence (a 50 ms-step fleet and a 5 s-step fleet get sane
        thresholds from the same default). No cadence estimate, no auto
        rule — better silent than noisy."""
        if self.straggler_lag_s > 0:
            return self.straggler_lag_s
        if step_dur is not None and step_dur > 0:
            return self.straggler_lag_x * step_dur
        return math.inf


def _finite(x: Optional[float]) -> bool:
    return x is not None and isinstance(x, (int, float)) and math.isfinite(x)


class AnomalyMonitor:
    """Stateful rule evaluator over the per-step (loss, telemetry) stream.

    ``metrics`` is a MetricsLogger (or None for in-memory use);
    ``timeline`` an optional TimelineRecorder; ``rho`` the configured
    density for sparse modes (None disables the density/age rules);
    ``halt_on`` one of None | "warn" | "error" — the minimum severity
    that raises AnomalyHalt."""

    def __init__(self, metrics=None, rho: Optional[float] = None,
                 halt_on: Optional[str] = None,
                 thresholds: Optional[Thresholds] = None,
                 timeline=None):
        if halt_on is not None and halt_on not in _SEVERITY_RANK:
            raise ValueError(
                f"halt_on={halt_on!r} must be one of "
                f"{sorted(_SEVERITY_RANK)} or None")
        self.metrics = metrics
        self.timeline = timeline
        self.rho = rho
        self.halt_on = halt_on
        # Recovery claim hook (resilience/policy.py): called with each
        # fired event; returning True means a recovery action will
        # handle it, which suppresses the halt for that event (the
        # record still lands, tagged claimed=True). None = detect-only.
        self.recovery = None
        self.th = thresholds or Thresholds()
        self.events: List[Dict[str, Any]] = []
        # EWMA state (loss mean/var, residual norm) + sample counts.
        self._loss_mean: Optional[float] = None
        self._loss_var = 0.0
        self._loss_n = 0
        self._res_mean: Optional[float] = None
        self._res_n = 0
        # Per-rank EWMA of sync-point lag (seconds), fed by observe_ranks
        # from the fleet merger; public — fleet straggler rows report it.
        self.rank_lag_ewma: Dict[int, float] = {}
        self._rank_lag_n: Dict[int, int] = {}
        # Refits seen so far, fed by the comm calibrator — the drift
        # rule arms only after comm_drift_warmup prior refits.
        self._comm_fit_n = 0
        # Compile-plane state (observe_compile): polls seen so far —
        # recompile_storm arms only after recompile_warmup prior polls.
        self._compile_n = 0
        # Memory-plane state (observe_memory): last live-bytes sample,
        # the current growth streak, and the per-rule latches (leak
        # fires once per monotonic run; headroom once per crossing).
        self._mem_last: Optional[float] = None
        self._mem_grow = 0
        self._mem_leak_fired = False
        self._headroom_over = False
        # Critical-path state (observe_critpath): the established modal
        # critical stage, plus the current differing streak and the
        # stage it has settled on. The first observation sets the modal
        # stage (inherent warmup — nothing can fire before a modal
        # stage exists to shift FROM).
        self._crit_modal: Optional[str] = None
        self._crit_streak = 0
        self._crit_streak_stage: Optional[str] = None
        # Goodput state (observe_goodput): EWMA of the run's cumulative
        # goodput_frac, observations seen, and the current below-
        # threshold streak.
        self._gp_ewma: Optional[float] = None
        self._gp_n = 0
        self._gp_streak = 0
        # Link-plane state (observe_links): per-link consecutive
        # degraded-window streaks. A link leaving the offender set
        # drops its streak entirely (re-arm on recovery).
        self._link_streaks: Dict[str, int] = {}
        # Forecast-plane state (observe_forecast): the current
        # consecutive hindcast-drifted streak. Recovery resets it.
        self._fc_streak = 0

    # ---------------------------------------------------------- the rules
    def _check(self, step: int, loss: Optional[float],
               telemetry: Optional[Dict[str, float]],
               max_residual_age: Optional[float]) -> List[Dict[str, Any]]:
        th = self.th
        out: List[Dict[str, Any]] = []

        def fire(rule, severity, value, threshold, message):
            out.append({
                "rule": rule, "severity": severity, "step": step,
                "value": round(float(value), 6) if _finite(value) else None,
                "threshold": (round(float(threshold), 6)
                              if math.isfinite(threshold) else None),
                "message": message,
            })

        if loss is not None and not _finite(loss):
            fire("nan_loss", "error", loss, math.nan,
                 f"non-finite loss at step {step}")
        elif _finite(loss):
            if (self._loss_n >= th.loss_warmup and self._loss_var > 0):
                z = (loss - self._loss_mean) / math.sqrt(self._loss_var)
                if z > th.loss_spike_z:
                    fire("loss_spike", "warn", z, th.loss_spike_z,
                         f"loss {loss:.4g} is {z:.1f} sigma above its "
                         f"EWMA {self._loss_mean:.4g}")
            a = th.loss_ewma_alpha
            if self._loss_mean is None:
                self._loss_mean = float(loss)
            else:
                d = float(loss) - self._loss_mean
                self._loss_mean += a * d
                self._loss_var = (1 - a) * (self._loss_var + a * d * d)
            self._loss_n += 1

        tel = telemetry or {}
        dens = tel.get("achieved_density")
        if (self.rho and _finite(dens)
                and dens < th.density_collapse_frac * self.rho):
            fire("density_collapse", "warn", dens,
                 th.density_collapse_frac * self.rho,
                 f"achieved density {dens:.3g} collapsed below "
                 f"{th.density_collapse_frac:g} x rho={self.rho:g}")

        res = tel.get("residual_norm")
        if _finite(res):
            if (self._res_n >= th.loss_warmup and self._res_mean
                    and res > th.residual_blowup_x * self._res_mean):
                fire("residual_blowup", "warn", res,
                     th.residual_blowup_x * self._res_mean,
                     f"residual norm {res:.4g} blew past "
                     f"{th.residual_blowup_x:g} x EWMA "
                     f"{self._res_mean:.4g}")
            a = th.loss_ewma_alpha
            self._res_mean = (float(res) if self._res_mean is None
                              else self._res_mean
                              + a * (float(res) - self._res_mean))
            self._res_n += 1

        age_max = th.age_max(self.rho)
        if _finite(max_residual_age) and max_residual_age > age_max:
            fire("residual_age_runaway", "warn", max_residual_age, age_max,
                 f"max per-layer mean residual age {max_residual_age:.0f} "
                 f"steps exceeds {age_max:.0f} (starved coordinates)")
        return out

    # ------------------------------------------------- straggler (fleet)
    def _check_ranks(self, step: int, lags: Dict[int, float],
                     step_dur: Optional[float]) -> List[Dict[str, Any]]:
        th = self.th
        threshold = th.straggler_threshold(step_dur)
        out: List[Dict[str, Any]] = []
        for rank in sorted(lags):
            lag = float(lags[rank])
            if not _finite(lag):
                continue
            # Arm-before-update, like residual_blowup: a rank must have
            # been late for straggler_warmup prior merged steps before
            # its current EWMA can fire — one slow step never does.
            ewma = self.rank_lag_ewma.get(rank)
            n = self._rank_lag_n.get(rank, 0)
            if (n >= th.straggler_warmup and ewma is not None
                    and ewma > threshold):
                out.append({
                    "rule": "straggler_persistent", "severity": "warn",
                    "step": step, "value": round(ewma, 6),
                    "threshold": (round(threshold, 6)
                                  if math.isfinite(threshold) else None),
                    "rank_behind": rank,
                    "message": (f"rank {rank} EWMA sync lag {ewma:.3g}s "
                                f"exceeds {threshold:.3g}s over {n} "
                                "merged steps (persistent straggler)"),
                })
            a = th.straggler_ewma_alpha
            self.rank_lag_ewma[rank] = (
                lag if ewma is None else ewma + a * (lag - ewma))
            self._rank_lag_n[rank] = n + 1
        return out

    # --------------------------------------------- comm model drift (calib)
    def _check_comm_model(self, step: int, alpha_ms: Optional[float],
                          beta_gbps: Optional[float],
                          ref_alpha_ms: Optional[float],
                          ref_beta_gbps: Optional[float],
                          fit_source: Optional[str]
                          ) -> List[Dict[str, Any]]:
        th = self.th
        worst = None  # (factor, name, fit, ref)
        for name, fit, ref in (("alpha_ms", alpha_ms, ref_alpha_ms),
                               ("beta_gbps", beta_gbps, ref_beta_gbps)):
            if not _finite(fit) or not _finite(ref):
                continue
            # Floor both sides so a fit collapsing to ~0 reads as a huge
            # finite factor instead of a ZeroDivisionError.
            a, b = max(float(fit), 1e-6), max(float(ref), 1e-6)
            factor = max(a / b, b / a)
            if worst is None or factor > worst[0]:
                worst = (factor, name, fit, ref)
        out: List[Dict[str, Any]] = []
        # Arm-before-update, like the straggler rule: the first
        # comm_drift_warmup refits (the fit is still converging on few
        # samples) can never fire.
        if (worst is not None and self._comm_fit_n >= th.comm_drift_warmup
                and worst[0] > th.comm_drift_x):
            factor, name, fit, ref = worst
            src = f" (planner fit: {fit_source})" if fit_source else ""
            out.append({
                "rule": "comm_model_drift", "severity": "warn",
                "step": step, "value": round(factor, 6),
                "threshold": round(th.comm_drift_x, 6),
                "param": name,
                "message": (f"live {name} fit {float(fit):.4g} is "
                            f"{factor:.3g}x off the planner's committed "
                            f"{float(ref):.4g}{src} — the comm model "
                            "that priced this run's schedule is stale"),
            })
        if worst is not None:
            self._comm_fit_n += 1
        return out

    # ---------------------------------------------- compile plane (memwatch)
    def _check_compile(self, step: int, cache_size: Optional[int],
                       grew: bool) -> List[Dict[str, Any]]:
        th = self.th
        out: List[Dict[str, Any]] = []
        # Arm-before-update, like the drift rule: growth observed within
        # the first recompile_warmup polls is warm-up compilation (a new
        # dispatch shape the run was always going to trace), not a storm.
        if grew and self._compile_n >= th.recompile_warmup:
            out.append({
                "rule": "recompile_storm", "severity": "warn",
                "step": step,
                "value": (round(float(cache_size), 6)
                          if _finite(cache_size) else None),
                "threshold": round(float(th.recompile_warmup), 6),
                "message": (f"jit executable cache grew to {cache_size} "
                            f"entries at step {step} after "
                            f"{self._compile_n} warm polls — a drifting "
                            "dispatch shape is retracing the hot step"),
            })
        self._compile_n += 1
        return out

    # ----------------------------------------------- memory plane (memwatch)
    def _check_memory(self, step: int, live_bytes: Optional[float],
                      bytes_in_use: Optional[float],
                      bytes_limit: Optional[float]
                      ) -> List[Dict[str, Any]]:
        th = self.th
        out: List[Dict[str, Any]] = []
        if _finite(live_bytes):
            if self._mem_last is not None and live_bytes > self._mem_last:
                self._mem_grow += 1
            else:
                # A plateau or shrink resets both the streak and the
                # latch — the NEXT monotonic run may fire again.
                self._mem_grow = 0
                self._mem_leak_fired = False
            self._mem_last = float(live_bytes)
            if (self._mem_grow >= th.mem_leak_windows
                    and not self._mem_leak_fired):
                self._mem_leak_fired = True
                out.append({
                    "rule": "device_mem_leak", "severity": "warn",
                    "step": step, "value": round(float(live_bytes), 6),
                    "threshold": round(float(th.mem_leak_windows), 6),
                    "message": (f"live device bytes grew for "
                                f"{self._mem_grow} consecutive windows "
                                f"to {live_bytes:.4g} — buffers are "
                                "accumulating (leak or unbounded cache)"),
                })
        if (_finite(bytes_in_use) and _finite(bytes_limit)
                and bytes_limit > 0):
            frac = float(bytes_in_use) / float(bytes_limit)
            if frac > th.hbm_headroom_frac:
                if not self._headroom_over:
                    self._headroom_over = True
                    out.append({
                        "rule": "hbm_headroom", "severity": "warn",
                        "step": step, "value": round(frac, 6),
                        "threshold": round(th.hbm_headroom_frac, 6),
                        "message": (f"device memory {frac:.1%} of "
                                    f"bytes_limit exceeds "
                                    f"{th.hbm_headroom_frac:.0%} — the "
                                    "next allocation spike can OOM"),
                    })
            else:
                self._headroom_over = False
        return out

    # ------------------------------------------- critical path (fleet)
    def _check_critpath(self, step: int, crit_stage: Optional[str]
                        ) -> List[Dict[str, Any]]:
        th = self.th
        out: List[Dict[str, Any]] = []
        if not crit_stage:
            return out
        if self._crit_modal is None:
            # Inherent warmup: the first joined step ESTABLISHES the
            # modal stage; there is nothing to shift from yet.
            self._crit_modal = crit_stage
            return out
        if crit_stage == self._crit_modal:
            self._crit_streak = 0
            self._crit_streak_stage = None
            return out
        # Differing stage: extend the streak only while it stays on ONE
        # new stage — a noisy alternation (comm, wait, comm, ...) is not
        # a shift, it's churn, and restarts the count.
        if crit_stage == self._crit_streak_stage:
            self._crit_streak += 1
        else:
            self._crit_streak_stage = crit_stage
            self._crit_streak = 1
        if self._crit_streak >= th.critpath_shift_windows:
            out.append({
                "rule": "critpath_shift", "severity": "warn",
                "step": step, "value": float(self._crit_streak),
                "threshold": round(float(th.critpath_shift_windows), 6),
                "from_stage": self._crit_modal, "to_stage": crit_stage,
                "message": (f"global critical stage shifted "
                            f"{self._crit_modal}->{crit_stage} for "
                            f"{self._crit_streak} consecutive joined "
                            "steps — the step's bottleneck moved"),
            })
            # Adopt the new stage and re-arm: the next shift is judged
            # against what the fleet is NOW bounded by.
            self._crit_modal = crit_stage
            self._crit_streak = 0
            self._crit_streak_stage = None
        return out

    # ------------------------------------------------ goodput (ledger)
    def _check_goodput(self, step: int, goodput_frac: Optional[float]
                       ) -> List[Dict[str, Any]]:
        th = self.th
        out: List[Dict[str, Any]] = []
        if not _finite(goodput_frac):
            return out
        frac = float(goodput_frac)
        # Arm-before-update, like the straggler/drift rules: the first
        # goodput_warmup ledger records (startup-dominated fractions)
        # establish the EWMA and can never fire; afterwards, a record
        # below goodput_collapse_frac x the EWMA extends the streak, a
        # recovered record resets it.
        if (self._gp_n >= th.goodput_warmup and self._gp_ewma is not None
                and self._gp_ewma > 0
                and frac < th.goodput_collapse_frac * self._gp_ewma):
            self._gp_streak += 1
        else:
            self._gp_streak = 0
        if self._gp_streak >= th.goodput_collapse_windows:
            out.append({
                "rule": "goodput_collapse", "severity": "warn",
                "step": step, "value": round(frac, 6),
                "threshold": round(
                    th.goodput_collapse_frac * self._gp_ewma, 6),
                "message": (f"goodput_frac {frac:.3g} stayed below "
                            f"{th.goodput_collapse_frac:g} x its EWMA "
                            f"{self._gp_ewma:.3g} for "
                            f"{self._gp_streak} consecutive ledger "
                            "records — wall-clock has stopped buying "
                            "training progress"),
            })
            # Re-arm: the EWMA keeps updating with the collapsed
            # fractions below, so a sustained new level is adopted and
            # only a FURTHER collapse fires again.
            self._gp_streak = 0
        a = th.goodput_ewma_alpha
        self._gp_ewma = (frac if self._gp_ewma is None
                         else self._gp_ewma + a * (frac - self._gp_ewma))
        self._gp_n += 1
        return out

    # ------------------------------------------------- link plane (linkmap)
    def _check_links(self, step: int, ewma_ms_by_link: Dict[str, float]
                     ) -> List[Dict[str, Any]]:
        th = self.th
        out: List[Dict[str, Any]] = []
        finite = {str(k): float(v) for k, v in ewma_ms_by_link.items()
                  if _finite(v)}
        # A one-link map has no fleet to compare against (worst == only
        # == median); the rule needs at least two links to mean anything.
        if len(finite) < 2:
            self._link_streaks.clear()
            return out
        vals = sorted(finite.values())
        mid = len(vals) // 2
        median = (vals[mid] if len(vals) % 2
                  else 0.5 * (vals[mid - 1] + vals[mid]))
        if median <= 0:
            return out
        offenders = {k: v for k, v in finite.items()
                     if v > th.link_degraded_x * median}
        # Recovery re-arms: a link back under the threshold loses its
        # streak entirely, so the NEXT degradation starts from zero.
        for key in list(self._link_streaks):
            if key not in offenders:
                del self._link_streaks[key]
        for key in sorted(offenders):
            v = offenders[key]
            n = self._link_streaks.get(key, 0) + 1
            self._link_streaks[key] = n
            if n < th.link_degraded_windows or out:
                continue  # streak still building, or already firing once
            # Fire once per streak, then re-arm this link: a SUSTAINED
            # degradation fires again only after another full streak.
            self._link_streaks[key] = 0
            axis, _, pair = key.partition(":")
            lo, _, hi = pair.partition("-")
            ev = {
                "rule": "link_degraded", "severity": "warn", "step": step,
                "value": round(v / median, 6),
                "threshold": round(th.link_degraded_x, 6),
                "link": key, "axis": axis,
                "ewma_ms": round(v, 6),
                "fleet_median_ms": round(median, 6),
                "windows": n,
                "message": (f"link {key} EWMA {v:.4g} ms stayed above "
                            f"{th.link_degraded_x:g} x the fleet median "
                            f"{median:.4g} ms for {n} consecutive "
                            "windows — that hop degraded, not just "
                            "'some rank is slow'"),
            }
            try:
                ev["src"], ev["dst"] = int(lo), int(hi)
            except ValueError:
                pass
            out.append(ev)
        return out

    # ------------------------------------------- forecast plane (forecast)
    def _check_forecast(self, step: int, err_x: Optional[float]
                        ) -> List[Dict[str, Any]]:
        th = self.th
        out: List[Dict[str, Any]] = []
        if not _finite(err_x):
            return out
        err = float(err_x)
        # Streak-is-the-warmup, like link_degraded: a capture whose
        # hindcast error exceeds the bound extends the streak, a
        # recovered capture resets it, and nothing fires before
        # forecast_drift_windows consecutive drifted captures.
        if err > th.forecast_drift_x:
            self._fc_streak += 1
        else:
            self._fc_streak = 0
        if self._fc_streak >= th.forecast_drift_windows:
            n = self._fc_streak
            # Fire once per streak, then re-arm: a model that STAYS
            # wrong fires again only after another full streak.
            self._fc_streak = 0
            out.append({
                "rule": "forecast_drift", "severity": "warn",
                "step": step, "value": round(err, 6),
                "threshold": round(th.forecast_drift_x, 6),
                "windows": n,
                "message": (f"hindcast error {err:.3g}x stayed beyond "
                            f"{th.forecast_drift_x:g}x for {n} "
                            "consecutive captures — the forecast model "
                            "no longer explains the run it was fitted "
                            "on; its scale-out recommendations are not "
                            "evidence"),
            })
        return out

    # ------------------------------------------------------------- public
    def _emit(self, fired: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """Record, persist (fsync'd), mark on the timeline, and — after
        everything is durably written — raise if any event reaches the
        halt severity. Shared by observe and observe_ranks."""
        halting = None
        for ev in fired:
            if ev.get("rule") not in RULES:
                raise ValueError(
                    f"unregistered anomaly rule {ev.get('rule')!r} — "
                    "add it to obs/events.RULES (and the README event "
                    "table) before emitting it")
            # Offer the event to the recovery layer BEFORE the halt
            # decision: a claimed event is about to be recovered from,
            # so halting on it would defeat the policy. The claim is
            # recorded on the event itself (only when a recovery layer
            # exists — detect-only runs keep byte-identical records).
            if self.recovery is not None:
                ev["claimed"] = bool(self.recovery(ev))
            self.events.append(ev)
            if self.metrics is not None:
                self.metrics.log("event", flush=True, **ev)
            if self.timeline is not None:
                self.timeline.instant(f"event:{ev['rule']}", args=ev)
            if (self.halt_on is not None and halting is None
                    and not ev.get("claimed")
                    and _SEVERITY_RANK[ev["severity"]]
                    >= _SEVERITY_RANK[self.halt_on]):
                halting = ev
        if halting is not None:
            raise AnomalyHalt(halting)
        return fired

    def observe(self, step: int, loss: Optional[float] = None,
                telemetry: Optional[Dict[str, float]] = None,
                max_residual_age: Optional[float] = None
                ) -> List[Dict[str, Any]]:
        """Evaluate every rule against one step's synced scalars; emit
        and return the fired events. Raises AnomalyHalt AFTER all records
        are flushed when any event reaches the halt severity."""
        return self._emit(self._check(step, loss, telemetry,
                                      max_residual_age))

    def observe_ranks(self, step: int, lags: Dict[int, float],
                      step_dur: Optional[float] = None
                      ) -> List[Dict[str, Any]]:
        """Evaluate the straggler rule against one merged step's per-rank
        sync-point lags (seconds behind the first rank, from the fleet
        merger). Same emit/halt contract as observe — a persistent
        straggler trips --obs-halt-on warn exactly like a loss spike."""
        return self._emit(self._check_ranks(step, dict(lags), step_dur))

    def observe_comm_model(self, step: int, alpha_ms: Optional[float],
                           beta_gbps: Optional[float], *,
                           ref_alpha_ms: Optional[float] = None,
                           ref_beta_gbps: Optional[float] = None,
                           fit_source: Optional[str] = None
                           ) -> List[Dict[str, Any]]:
        """Evaluate the comm_model_drift rule against one refit of the
        live calibrator (obs/calib.py) vs the planner's committed
        reference fit. Same emit/halt contract as observe — a drifted
        comm model trips --obs-halt-on warn like any other anomaly."""
        return self._emit(self._check_comm_model(
            step, alpha_ms, beta_gbps, ref_alpha_ms, ref_beta_gbps,
            fit_source))

    def observe_compile(self, step: int, *,
                        cache_size: Optional[int] = None,
                        grew: bool = False) -> List[Dict[str, Any]]:
        """Evaluate the recompile_storm rule against one compile-watch
        poll (obs/memwatch.py): the jitted step's executable-cache size
        and whether it grew since the previous poll. Same emit/halt
        contract as observe — a recompile storm trips --obs-halt-on warn
        like any other anomaly."""
        return self._emit(self._check_compile(step, cache_size, grew))

    def observe_memory(self, step: int, *,
                       live_bytes: Optional[float] = None,
                       bytes_in_use: Optional[float] = None,
                       bytes_limit: Optional[float] = None
                       ) -> List[Dict[str, Any]]:
        """Evaluate the device_mem_leak / hbm_headroom rules against one
        live-memory window (obs/memwatch.py sampling). Backends without
        memory_stats feed live_bytes only — the headroom rule simply
        never arms there. Same emit/halt contract as observe."""
        return self._emit(self._check_memory(step, live_bytes,
                                             bytes_in_use, bytes_limit))

    def observe_critpath(self, step: int, *,
                         crit_stage: Optional[str] = None
                         ) -> List[Dict[str, Any]]:
        """Evaluate the critpath_shift rule against one fleet-joined
        step's global critical stage (obs/critpath.py critical_path via
        the fleet merger). Same emit/halt contract as observe — a moved
        bottleneck trips --obs-halt-on warn like any other anomaly."""
        return self._emit(self._check_critpath(step, crit_stage))

    def observe_goodput(self, step: int, *,
                        goodput_frac: Optional[float] = None
                        ) -> List[Dict[str, Any]]:
        """Evaluate the goodput_collapse rule against one periodic
        ledger record's cumulative goodput_frac (obs/goodput.py). Same
        emit/halt contract as observe — the ledger writes its durable
        record BEFORE feeding the monitor, so the decomposition that
        explains the collapse survives the exit-44 halt."""
        return self._emit(self._check_goodput(step, goodput_frac))

    def observe_links(self, step: int, ewma_ms_by_link: Dict[str, float]
                      ) -> List[Dict[str, Any]]:
        """Evaluate the link_degraded rule against one weather-map
        snapshot: {link key ("axis:lo-hi") -> EWMA latency ms} from
        LinkMap (obs/linkmap.py). Same emit/halt contract as observe —
        LinkMap writes its durable linkmap record BEFORE calling this,
        so the evidence naming the degraded hop survives the exit-44
        halt."""
        return self._emit(self._check_links(step, dict(ewma_ms_by_link)))

    def observe_forecast(self, step: int, *,
                         err_x: Optional[float] = None
                         ) -> List[Dict[str, Any]]:
        """Evaluate the forecast_drift rule against one forecast
        capture's hindcast error factor (obs/forecast.py). Same
        emit/halt contract as observe — StepForecaster writes its
        durable forecast record BEFORE calling this, so the prediction
        that failed survives the exit-44 halt."""
        return self._emit(self._check_forecast(step, err_x))

    def summary(self) -> Dict[str, int]:
        """{rule: count} over the monitor's lifetime (test/report aid)."""
        out: Dict[str, int] = {}
        for ev in self.events:
            out[ev["rule"]] = out.get(ev["rule"], 0) + 1
        return out
