"""Distributed critical path: per-step (rank, stage) attribution.

The paper's wall-clock argument (arXiv:1901.04359 §5) decomposes a step
into T_compute/T_select/T_comm, and the fleet plane (obs/fleet.py) can
already say which RANK was slowest — but neither can say which STAGE on
which rank actually bounded the step, nor how much of T_comm was wire
versus waiting at the collective for a skewed peer. This module closes
both gaps:

  wait split — the comm class's wall-clock union on one rank is split
      into a modeled-wire prefix and a trailing ``wait`` remainder: the
      ledger's alpha-beta model (obs/ledger.py) prices the bytes the
      step actually moved, and whatever span the collective occupied
      beyond that is skew-wait, not wire. The split is proportional
      across the union's intervals (each interval is cut at the same
      wire fraction), which keeps the segments well-ordered without
      pretending to know which tree round absorbed the skew.

  stage segments — a compact per-step record of ordered
      ``{stage, t0_us, t1_us}`` intervals over STAGES =
      (compute, select, comm, wait), rank-relative (earliest t0 == 0),
      shipped as the durable ``critpath`` metrics kind through the
      per-rank shard files.

  critical path — a deterministic backward walk over the per-rank
      segment sets joined at one step: start from the rank that defines
      the step's wall time and walk toward 0, preferring busy
      (non-wait) segments and handing off to whichever other rank was
      busy whenever the current rank was merely waiting. The chain of
      (rank, stage) pieces is the step's critical path; ``crit_frac``
      (chain length / wall) says how much of the step the
      reconstruction explains — gaps (profiler blind spots) lower it
      rather than being papered over.

Why a backward walk: the END of the step is unambiguous (the last rank
to finish defines it), while the start is convention. Walking backward
from the defining rank answers "what was the fleet bounded by just
before t" at every t, which is exactly the eviction/deadline evidence
ROADMAP items 1 and 4 need. All tie-breaks are deterministic (lowest
rank, then STAGES order) so fixtures and tests can assert exact chains.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from gtopkssgd_tpu.obs import trace_attr
from gtopkssgd_tpu.obs import ledger

# Stage universe, in tie-break order: when two stages tie on chain (or
# local-budget) time, the earlier one here wins. ``wait`` is last so a
# tie never blames skew over real work.
STAGES = ("compute", "select", "comm", "wait")

_EPS = 1e-6


# ------------------------------------------------------------ wait split

def wait_split(comm_iv: Sequence[Tuple[float, float]],
               wire_us: float
               ) -> Tuple[List[Tuple[float, float]],
                          List[Tuple[float, float]]]:
    """Split the comm wall-clock union into (wire, wait) interval lists.

    ``wire_us`` is the ledger-modeled wire time for the bytes this step
    moved; the comm union's first ``wire_us`` worth of span (allocated
    proportionally per union interval) stays ``comm``, the trailing
    remainder becomes ``wait``. wire_us >= union length means no wait
    (the model already explains the whole span); wire_us <= 0 means the
    whole span is wait (nothing was supposed to be on the wire)."""
    union = trace_attr._interval_union(list(comm_iv))
    total = sum(e - s for s, e in union)
    if total <= 0:
        return [], []
    wire_frac = min(1.0, max(0.0, float(wire_us) / total))
    wire: List[Tuple[float, float]] = []
    wait: List[Tuple[float, float]] = []
    for s, e in union:
        cut = s + (e - s) * wire_frac
        if cut - s > _EPS:
            wire.append((s, cut))
        if e - cut > _EPS:
            wait.append((cut, e))
    return wire, wait


def stage_segments(iv_by_class: Mapping[str, Sequence[Tuple[float, float]]],
                   wire_us: float,
                   normalize: bool = True,
                   fill_gaps: bool = False) -> List[Dict[str, Any]]:
    """Ordered ``{stage, t0_us, t1_us}`` segments from per-class raw
    wall intervals (trace_attr's ``op_iv``/``span_iv`` shape). compute
    and select are their interval unions; comm is wait-split against
    ``wire_us``. ``normalize`` shifts the earliest t0 to 0 so records
    are rank-relative and joinable across hosts with unsynced clocks.

    ``fill_gaps`` is for REAL profiler captures, where op events never
    tile the dispatch window (scheduler gaps between op executions are
    framework overhead, not a distinct stage): each uncovered gap is
    absorbed into the stage that PRECEDES it — work that stage had not
    yet retired, or a wait the collective had not yet released — and
    adjacent same-stage segments are then coalesced, so the record is
    compact (one segment per stage transition) and the segments tile
    the measured step wall. Synthetic/fixture segments keep the default
    (gaps stay visible and lower ``crit_frac`` honestly)."""
    raw: List[Tuple[float, str, float]] = []  # (t0, stage, t1)
    for stage in ("compute", "select"):
        for s, e in trace_attr._interval_union(
                list(iv_by_class.get(stage, ()))):
            raw.append((s, stage, e))
    wire, wait = wait_split(iv_by_class.get("comm", ()), wire_us)
    for s, e in wire:
        raw.append((s, "comm", e))
    for s, e in wait:
        raw.append((s, "wait", e))
    if not raw:
        return []
    t_min = min(s for s, _, _ in raw) if normalize else 0.0
    segs = [{"stage": stage,
             "t0_us": round(s - t_min, 1),
             "t1_us": round(e - t_min, 1)}
            for s, stage, e in raw]
    segs.sort(key=lambda g: (g["t0_us"], g["t1_us"],
                             STAGES.index(g["stage"])))
    if not fill_gaps:
        return segs
    out: List[Dict[str, Any]] = []
    last = -1  # index of the segment holding the latest end so far
    for seg in segs:
        if out and seg["t0_us"] > out[last]["t1_us"] + _EPS:
            # Uncovered gap: the stage that ran latest owns it.
            out[last]["t1_us"] = seg["t0_us"]
        if (out and out[last]["stage"] == seg["stage"]
                and seg["t0_us"] <= out[last]["t1_us"] + _EPS):
            out[last]["t1_us"] = max(out[last]["t1_us"], seg["t1_us"])
        else:
            out.append(seg)
            if last < 0 or seg["t1_us"] >= out[last]["t1_us"]:
                last = len(out) - 1
    return out


def coarsen(segments: Sequence[Mapping[str, Any]],
            min_us: float) -> List[Dict[str, Any]]:
    """Compact a (filled) segment list for the durable record: absorb
    segments shorter than ``min_us`` into their predecessor and merge
    same-stage neighbors, leaving one segment per sustained stage
    transition. Micro-flicker (op-granularity interleave of classes on
    a real trace) changes owner here, so per-stage TOTALS must be
    computed from the fine list (``build_record(..., totals=...)``) —
    the coarse list is the chain-walk/timeline view, not the budget."""
    out: List[Dict[str, Any]] = []
    for seg in segments:
        seg = dict(seg)
        if out and (seg["stage"] == out[-1]["stage"]
                    or float(seg["t1_us"]) - float(seg["t0_us"])
                    < float(min_us)):
            out[-1]["t1_us"] = max(out[-1]["t1_us"], seg["t1_us"])
        else:
            out.append(seg)
    return out


def stage_totals(segments: Sequence[Mapping[str, Any]]
                 ) -> Dict[str, float]:
    """Per-stage summed lengths (µs) of a segment list."""
    tot = {s: 0.0 for s in STAGES}
    for seg in segments:
        st = seg.get("stage")
        if st in tot:
            tot[st] += float(seg["t1_us"]) - float(seg["t0_us"])
    return tot


def dominant_stage(stage_us: Mapping[str, float]) -> Optional[str]:
    """Stage with the largest total; STAGES order breaks ties; None
    when everything is zero."""
    best, best_us = None, 0.0
    for s in STAGES:
        us = float(stage_us.get(s, 0.0))
        if us > best_us + _EPS:
            best, best_us = s, us
    return best


# ------------------------------------------------------- record building

def build_record(segments: Sequence[Mapping[str, Any]],
                 step: Optional[int] = None,
                 totals: Optional[Mapping[str, float]] = None
                 ) -> Dict[str, Any]:
    """The flat per-rank ``critpath`` record (no 'kind' key — callers
    log it as kind="critpath"): segment list + per-stage totals +
    wall/wait summary. ``wait_frac`` is wait over this rank's wall —
    the share of the step this rank spent blocked at collectives.
    ``step`` may be stamped later by the caller (trace_attr doesn't
    know it at attribution time). Pass ``totals`` (stage_totals of the
    FINE segment list) when ``segments`` has been coarsened — the
    coarse view reassigns micro-flicker and must not skew the budget."""
    segs = [dict(s) for s in segments]
    totals = dict(totals) if totals is not None else stage_totals(segs)
    totals = {s: float(totals.get(s, 0.0)) for s in STAGES}
    wall = max((float(s["t1_us"]) for s in segs), default=0.0)
    wait_us = totals["wait"]
    rec = {} if step is None else {"step": step}
    return {
        **rec,
        "wall_us": round(wall, 1),
        "t_compute_us": round(totals["compute"], 1),
        "t_select_us": round(totals["select"], 1),
        "t_comm_wire_us": round(totals["comm"], 1),
        "t_wait_us": round(wait_us, 1),
        "wait_frac": round(wait_us / wall, 6) if wall > 0 else 0.0,
        "crit_stage": dominant_stage(totals),
        "segments": segs,
    }


def modeled_wire_us(manifest: Optional[Mapping[str, Any]],
                    probe_dir: Optional[str] = None,
                    nprocs: Optional[int] = None) -> Optional[float]:
    """Ledger-modeled per-step wire time in µs for this run's bytes —
    the wait split's budget. Reuses the ledger's manifest parser, fit
    loader and alpha-beta pricing verbatim so the split and the
    predicted-vs-measured ledger can never disagree on the model.
    None when the manifest can't parameterize the model."""
    params = ledger._manifest_params(manifest)
    if params is None:
        return None
    alpha_ms, beta_gbps = 0.0, ledger.DEFAULT_DCN_GBPS
    fit = ledger.load_alpha_beta(search_dir=probe_dir, nprocs=nprocs)
    if fit is not None:
        alpha_ms, beta_gbps = fit["alpha_ms"], fit["beta_gbps"]
    wm = ledger.wire_mode_for(params["mode"], params.get("schedule"),
                              bucketing=params.get("bucketing"))
    ms = ledger.predict_comm_ms(
        wm, params["p"], n=params["n"], k=params["k"],
        alpha_ms=alpha_ms, beta_gbps=beta_gbps,
        codec=params["codec"], buckets=params.get("buckets"))
    return ms * 1e3


# ------------------------------------------------------- critical path

def _covering(segs: Sequence[Mapping[str, Any]], t: float
              ) -> List[Mapping[str, Any]]:
    """Segments covering the instant just before ``t``."""
    return [s for s in segs
            if float(s["t0_us"]) < t - _EPS
            and float(s["t1_us"]) >= t - _EPS]


def _pick_busy(cands: Sequence[Mapping[str, Any]]
               ) -> Optional[Mapping[str, Any]]:
    """Latest-starting non-wait segment, tie-break STAGES order."""
    busy = [s for s in cands if s.get("stage") != "wait"]
    if not busy:
        return None
    return max(busy, key=lambda s: (float(s["t0_us"]),
                                    -STAGES.index(s["stage"])))


def critical_path(segs_by_rank: Mapping[int, Sequence[Mapping[str, Any]]]
                  ) -> Dict[str, Any]:
    """The step-bounding chain of (rank, stage) segments.

    ``segs_by_rank`` maps rank → that rank's rank-relative stage
    segments for ONE step. Returns::

        {wall_us, crit_rank, crit_stage, crit_frac,
         chain: [{rank, stage, t0_us, t1_us}, ...],   # time order
         stage_us: {stage: chain µs},                 # chain budget
         blocked_us: {rank: total wait µs}}           # per-rank skew

    Walk: start at the wall (the latest rank end; ties → lowest rank)
    and move backward. At each instant the chain takes the current
    rank's latest-starting busy segment; when the current rank is only
    WAITING, the bound is whichever other rank was busy — hand off to
    the candidate whose busy segment ends latest (ties → lowest rank).
    If nobody was busy, the wait itself is the bound (pure skew/model
    error) and joins the chain. A t where NO rank has any segment is a
    profiler gap: jump to the latest segment end below t — the skipped
    span lowers ``crit_frac`` instead of being attributed to anyone.
    """
    ranks = sorted(segs_by_rank)
    ends = {r: max((float(s["t1_us"]) for s in segs_by_rank[r]),
                   default=0.0) for r in ranks}
    wall = max(ends.values(), default=0.0)
    blocked = {r: round(stage_totals(segs_by_rank[r])["wait"], 1)
               for r in ranks}
    out: Dict[str, Any] = {
        "wall_us": round(wall, 1), "crit_rank": None, "crit_stage": None,
        "crit_frac": 0.0, "chain": [], "stage_us": {},
        "blocked_us": blocked,
    }
    if wall <= 0:
        return out
    cur = min(r for r in ranks if ends[r] >= wall - _EPS)
    t = wall
    chain: List[Dict[str, Any]] = []
    while t > _EPS:
        cands = _covering(segs_by_rank[cur], t)
        seg = _pick_busy(cands)
        if seg is None and cands:
            # Current rank is waiting: hand off to a busy rank.
            best = None  # (end, -rank, rank, seg)
            for r in ranks:
                if r == cur:
                    continue
                other = _pick_busy(_covering(segs_by_rank[r], t))
                if other is None:
                    continue
                key = (float(other["t1_us"]), -r)
                if best is None or key > best[0]:
                    best = (key, r, other)
            if best is not None:
                cur, seg = best[1], best[2]
            else:
                # Everyone idle or waiting: the wait IS the bound.
                seg = max(cands,
                          key=lambda s: (float(s["t0_us"]),
                                         -STAGES.index(s["stage"])))
        if seg is None:
            # Gap: no segment on the current rank covers t. Jump to the
            # latest end <= t anywhere; the gap is unexplained time.
            best_end, best_rank = None, None
            for r in ranks:  # ties → lowest rank (sorted + strict >)
                for s in segs_by_rank[r]:
                    e = float(s["t1_us"])
                    if e < t - _EPS and (best_end is None
                                         or e > best_end + _EPS):
                        best_end, best_rank = e, r
            if best_end is None:
                break
            t, cur = best_end, best_rank
            continue
        t0 = float(seg["t0_us"])
        piece_t0 = max(0.0, t0)
        chain.append({"rank": cur, "stage": seg["stage"],
                      "t0_us": round(piece_t0, 1), "t1_us": round(t, 1)})
        t = piece_t0
    chain.reverse()
    # Merge adjacent same-(rank, stage) pieces (a handoff can split one
    # segment when the walk re-enters it).
    merged: List[Dict[str, Any]] = []
    for p in chain:
        if (merged and merged[-1]["rank"] == p["rank"]
                and merged[-1]["stage"] == p["stage"]
                and abs(merged[-1]["t1_us"] - p["t0_us"]) <= 1e-3):
            merged[-1]["t1_us"] = p["t1_us"]
        else:
            merged.append(dict(p))
    chain = merged
    stage_us = {s: 0.0 for s in STAGES}
    rank_us = {r: 0.0 for r in ranks}
    for p in chain:
        length = p["t1_us"] - p["t0_us"]
        stage_us[p["stage"]] += length
        rank_us[p["rank"]] += length
    covered = sum(stage_us.values())
    out["chain"] = chain
    out["stage_us"] = {s: round(us, 1) for s, us in stage_us.items()}
    out["crit_frac"] = round(min(1.0, covered / wall), 6)
    out["crit_stage"] = dominant_stage(stage_us)
    crit_rank, best_us = None, -1.0
    for r in ranks:  # tie → lowest rank (sorted order + strict >)
        if rank_us[r] > best_us + _EPS:
            crit_rank, best_us = r, rank_us[r]
    out["crit_rank"] = crit_rank
    return out


# ------------------------------------------------------------ formatting

def format_critpath(rows: Sequence[Mapping[str, Any]],
                    budgets: Optional[Mapping[int, Mapping[str, float]]]
                    = None) -> str:
    """Render fleet-joined critpath rows: per-step table, per-rank
    stage/wait budget, and the modal-path summary ``report critpath``
    prints."""
    lines: List[str] = []
    header = ["step", "crit_rank", "crit_stage", "crit_frac", "wall_ms",
              "chain"]
    table = []
    for r in rows:
        chain = " > ".join(
            f"r{p['rank']}:{p['stage']}" for p in r.get("chain", []))
        table.append([str(r.get("step")), f"r{r.get('crit_rank')}",
                      str(r.get("crit_stage")),
                      f"{float(r.get('crit_frac', 0.0)):.4f}",
                      f"{float(r.get('wall_us', 0.0)) / 1e3:.3f}",
                      chain[:72]])
    widths = [max(len(x[i]) for x in [header] + table)
              for i in range(len(header))] if table else []
    if table:
        lines.append("  ".join(c.ljust(w)
                               for c, w in zip(header, widths)))
        lines.append("  ".join("-" * w for w in widths))
        for x in table:
            lines.append("  ".join(c.ljust(w) for c, w in zip(x, widths)))
    else:
        lines.append("(no critpath rows)")
    if budgets:
        lines.append("")
        lines.append("per-rank budget (ms on chain by stage; "
                     "blocked = that rank's total wait):")
        bh = ["rank"] + list(STAGES) + ["blocked"]
        bt = []
        for r in sorted(budgets):
            b = budgets[r]
            bt.append([f"r{r}"]
                      + [f"{float(b.get(s, 0.0)) / 1e3:.3f}"
                         for s in STAGES]
                      + [f"{float(b.get('blocked_us', 0.0)) / 1e3:.3f}"])
        bw = [max(len(x[i]) for x in [bh] + bt) for i in range(len(bh))]
        lines.append("  ".join(c.ljust(w) for c, w in zip(bh, bw)))
        lines.append("  ".join("-" * w for w in bw))
        for x in bt:
            lines.append("  ".join(c.ljust(w) for c, w in zip(x, bw)))
    if rows:
        counts: Dict[str, int] = {}
        for r in rows:
            st = r.get("crit_stage")
            if st:
                counts[st] = counts.get(st, 0) + 1
        modal = dominant_stage({s: float(c) for s, c in counts.items()})
        mean_frac = sum(float(r.get("crit_frac", 0.0))
                        for r in rows) / len(rows)
        lines.append("")
        lines.append(
            f"modal critical stage: {modal}  "
            f"({counts.get(modal, 0)}/{len(rows)} steps)  "
            f"mean crit_frac={mean_frac:.4f}")
    return "\n".join(lines)
