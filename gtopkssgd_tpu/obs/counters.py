"""On-device training-health counters for the compression pipeline.

Everything here is traced INSIDE the jitted train step (pure jnp on
device-resident arrays — no host round-trips) and carried out through the
optimizer state's ``telemetry`` field, so the per-step numbers ride the
existing metrics path for every mode (gtopk, gtopk_layerwise, gtopk_hier,
allgather, dense).

The counter set is the paper's own analysis axis plus the residual
dynamics arXiv:1911.08772 shows convergence hinges on:

  grad_norm_pre    — L2 of the local gradient entering the pipeline
                     (post-clip, post ICI slice-sum in hier mode)
  grad_norm_post   — L2 of the averaged dense update actually applied
  residual_norm    — L2 of the error-feedback residual AFTER repair (the
                     v buffer under momentum correction)
  tau              — the top-k selection threshold: smallest selected
                     magnitude (0 in dense phases/modes)
  sent_elems       — actual NONZERO elements in the communicated set
                     (padding slots in a <k-nonzero step don't count)
  achieved_density — sent_elems / N vs. the requested rho
  wire_bytes       — the comm-volume model for this step's collective
                     (parallel.comm_bytes_per_step — O(k log P) gtopk,
                     O(k P) allgather, O(N) dense), a static per-step
                     constant that makes jsonl rows self-describing

All values are f32 scalars; under shard_map the optimizer pmeans them over
the dp axis so the stored telemetry is replicated (per-device quantities
like the residual norm become axis means, which is the number you want on
a dashboard anyway).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gtopkssgd_tpu.parallel import comm_bytes_per_step

Array = jax.Array

TELEMETRY_FIELDS = (
    "grad_norm_pre",
    "grad_norm_post",
    "residual_norm",
    "tau",
    "sent_elems",
    "achieved_density",
    "wire_bytes",
    "m_k",
    # Wire-level collective launches per optimizer step (f32 of a static
    # count, like wire_bytes): 0 at p=1, 1 for every single-merge wire,
    # B for the bucketed layerwise path, 2 for the hier mode's two
    # levels. The alpha side of the alpha-beta ledger: each launch pays
    # the per-collective latency that the bucketing DP optimizes, so the
    # bucket gate pins its >=3x merge-count reduction on this counter.
    "collective_count",
)

# Per-layer counter set (telemetry_layers=True). The mass-capture ratio
# m(k) = ||selected||^2 / ||acc||^2 and its per-layer skew are the
# quantities arXiv:1911.08772 ties to the top-k convergence gap;
# residual_age is the mean steps-since-a-coordinate-last-shipped, the
# staleness axis the whole-model residual norm cannot resolve.
LAYER_FIELDS = (
    "density",
    "tau",
    "grad_norm_pre",
    "grad_norm_post",
    "residual_norm",
    "residual_age",
    "m_k",
)

_MASS_EPS = 1e-30


def zero_telemetry() -> Dict[str, Array]:
    """The fixed telemetry structure at init (all zeros). init_fn uses this
    so the state pytree has an identical treedef at step 0 and step k."""
    return {f: jnp.zeros((), jnp.float32) for f in TELEMETRY_FIELDS}


def tree_l2(tree) -> Array:
    """L2 norm over every leaf of a pytree (flat arrays, per-leaf tuples,
    or a single array alike). Empty trees / zero-size leaves give 0."""
    leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "size")]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    total = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(total)


def residual_l2(residual) -> Array:
    """L2 of the error-feedback buffer. Under momentum correction the
    residual field is ``{"v": ..., "u": ...}``; v is the accumulated-
    velocity buffer that plays the residual's role (optimizer.py), so the
    norm reads v only — including u would double-count momentum mass."""
    if isinstance(residual, dict) and "v" in residual:
        residual = residual["v"]
    return tree_l2(residual)


def selected_tau(vals: Array) -> Array:
    """Top-k threshold from a selected-values buffer: the smallest NONZERO
    selected magnitude. Selection kernels pad value slots with 0.0 when
    fewer than k nonzeros exist; a plain min would report tau=0 for every
    such step and hide the real threshold."""
    mags = jnp.abs(vals)
    nz = mags > 0
    t = jnp.min(jnp.where(nz, mags, jnp.inf))
    return jnp.where(jnp.any(nz), t, 0.0).astype(jnp.float32)


def keep_tau(keep: Array, acc: Array) -> Array:
    """tau for the mask-form selection (compress_by_threshold): smallest
    kept magnitude, 0 when nothing is kept."""
    mags = jnp.abs(acc)
    t = jnp.min(jnp.where(keep, mags, jnp.inf))
    return jnp.where(jnp.any(keep), t, 0.0).astype(jnp.float32)


def sent_count(vals: Array) -> Array:
    """Actual nonzeros in a communicated value buffer (f32 scalar)."""
    return jnp.sum((vals != 0).astype(jnp.float32))


def make_telemetry(
    *,
    n: int,
    k: int,
    p: int,
    mode,
    ici_size: int = 1,
    codec="fp32",
    schedule=None,
    buckets=None,
    grad_norm_pre,
    grad_norm_post,
    residual_norm,
    tau,
    sent_elems,
    m_k=0.0,
) -> Dict[str, Array]:
    """Assemble the per-step telemetry dict (all f32 scalars).

    ``n``/``k``/``p``/``mode``/``ici_size``/``codec``/``schedule`` (the
    resolved wire plan's schedule, parallel.planner) are static
    trace-time values; ``wire_bytes`` therefore folds to a constant — the
    model volume for this step's collective from the one shared
    definition (parallel.comm_bytes_per_step), so the metric can never
    drift from the benchmark's comm model. With a quantized wire codec
    the constant is CODEC bytes (packed values + scales + bitpacked
    indices), not logical fp32 bytes.

    ``buckets`` — the bucketed layerwise path's ((n_b, k_b), ...) pairs
    (parallel.bucketing.BucketPlan.pairs) — makes ``wire_bytes`` the sum
    over the B merges actually issued (each over its bucket-local index
    space) and sets ``collective_count`` to B. Like wire_bytes, both are
    static: during a dense warm-up phase they still describe the sparse
    wire the run switches to."""
    sent = jnp.asarray(sent_elems, jnp.float32)
    if buckets:
        wire = sum(
            comm_bytes_per_step(mode, int(n_b), int(k_b), p,
                                ici_size=ici_size, codec=codec,
                                schedule=schedule)
            for n_b, k_b in buckets)
        n_coll = len(buckets) if p > 1 else 0
    else:
        wire = comm_bytes_per_step(mode, n, k, p, ici_size=ici_size,
                                   codec=codec, schedule=schedule)
        if p <= 1:
            n_coll = 0
        else:
            n_coll = 2 if (mode == "gtopk_hier" and ici_size > 1) else 1
    return {
        "grad_norm_pre": jnp.asarray(grad_norm_pre, jnp.float32),
        "grad_norm_post": jnp.asarray(grad_norm_post, jnp.float32),
        "residual_norm": jnp.asarray(residual_norm, jnp.float32),
        "tau": jnp.asarray(tau, jnp.float32),
        "sent_elems": sent,
        "achieved_density": sent / jnp.float32(max(1, n)),
        "wire_bytes": jnp.float32(wire),
        "m_k": jnp.asarray(m_k, jnp.float32),
        "collective_count": jnp.float32(n_coll),
    }


# --------------------------------------------------------------------------
# Per-layer counters (telemetry_layers). Everything below is still pure jnp
# traced inside the jitted step; layer identity is static trace-time
# structure (the grads pytree), so the only runtime cost is a handful of
# segment reductions over arrays the step already materializes.
# --------------------------------------------------------------------------


def layer_names(tree) -> Tuple[str, ...]:
    """Stable per-leaf names in jax.tree.flatten order — '/'-joined pytree
    key paths ('block1/conv1/kernel' for nested flax params). This is the
    SAME order ravel_pytree and the layerwise residual use, so index i of
    every [L] layer-stat array refers to names()[i]."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, _ in leaves:
        parts = []
        for k in path:
            parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
        out.append("/".join(parts) if parts else "param")
    return tuple(out)


def layer_sizes(tree) -> Tuple[int, ...]:
    """Per-leaf element counts in the same flatten order as layer_names."""
    return tuple(int(x.size) for x in jax.tree.leaves(tree))


def segment_ids(sizes: Sequence[int]) -> np.ndarray:
    """i32[N] coordinate->layer map for the flat [N] gradient layout — a
    trace-time numpy constant (XLA folds it), shared by every flat-mode
    segment reduction so layer boundaries cannot drift between fields."""
    return np.repeat(
        np.arange(len(sizes), dtype=np.int32), np.asarray(sizes, np.int64)
    )


def zero_layer_telemetry(sizes: Sequence[int], *, per_leaf_age: bool):
    """Zero per-layer structure for init_fn: [L] zeros per LAYER_FIELDS
    plus the residual-age buffer in the residual's own layout (flat [N]
    for flat modes, per-leaf tuple for layerwise) so the state treedef is
    identical at step 0 and step k."""
    L = len(sizes)
    if per_leaf_age:
        age = tuple(jnp.zeros((int(s),), jnp.float32) for s in sizes)
    else:
        age = jnp.zeros((int(sum(sizes)),), jnp.float32)
    return {
        "layers": {f: jnp.zeros((L,), jnp.float32) for f in LAYER_FIELDS},
        "age": age,
    }


def seg_l2(x: Array, seg: np.ndarray, L: int) -> Array:
    """Per-layer L2 norms of a flat [N] vector in one segment reduction."""
    x = x.astype(jnp.float32)
    return jnp.sqrt(jax.ops.segment_sum(
        x * x, seg, num_segments=L, indices_are_sorted=True))


def _tree_sq(tree) -> Array:
    return sum(
        jnp.sum(jnp.square(x.astype(jnp.float32)))
        for x in jax.tree.leaves(tree)
    )


def mass_ratio(acc, selected) -> Array:
    """Whole-model mass-capture ratio m(k) = ||selected||^2 / ||acc||^2
    (arXiv:1911.08772). Both args may be arrays or pytrees of arrays;
    ``selected`` may be the densified selection or just the selected
    values — only its mass matters."""
    return _tree_sq(selected) / jnp.maximum(_tree_sq(acc), _MASS_EPS)


def leaf_l2(arrs: Sequence[Array]) -> Array:
    """Stacked per-leaf L2 norms, f32[L] — the layerwise-mode counterpart
    of seg_l2 (one small reduction per leaf; no flat vector exists)."""
    return jnp.stack([
        jnp.sqrt(jnp.sum(jnp.square(a.astype(jnp.float32)))) for a in arrs
    ])


def selection_layer_stats(
    acc: Array, sel_dense: Array, seg: np.ndarray, L: int
) -> Tuple[Dict[str, Array], Array]:
    """Per-layer selection stats for the flat [N] layout.

    ``sel_dense`` is the locally-selected set densified (selected values
    in place, 0 elsewhere — the threshold path's ``acc - residual``, or a
    scatter of (vals, idx) for the index form). Returns
    ({sent, tau, m_k} as f32[L], whole-model m_k). A value-0 selection
    slot counts as not sent, matching sent_count's convention."""
    mask = sel_dense != 0
    sent = jax.ops.segment_sum(
        mask.astype(jnp.float32), seg, num_segments=L,
        indices_are_sorted=True)
    mags = jnp.abs(sel_dense)
    tau = jax.ops.segment_min(
        jnp.where(mask, mags, jnp.inf), seg, num_segments=L,
        indices_are_sorted=True)
    tau = jnp.where(jnp.isfinite(tau), tau, 0.0).astype(jnp.float32)
    acc32 = acc.astype(jnp.float32)
    sel32 = sel_dense.astype(jnp.float32)
    acc_sq = jax.ops.segment_sum(
        acc32 * acc32, seg, num_segments=L, indices_are_sorted=True)
    sel_sq = jax.ops.segment_sum(
        sel32 * sel32, seg, num_segments=L, indices_are_sorted=True)
    m_k = sel_sq / jnp.maximum(acc_sq, _MASS_EPS)
    whole = jnp.sum(sel_sq) / jnp.maximum(jnp.sum(acc_sq), _MASS_EPS)
    return {"sent": sent, "tau": tau, "m_k": m_k}, whole


def sparse_selection_layer_stats(
    acc: Array, vals: Array, idx: Array, seg: np.ndarray, L: int
) -> Tuple[Dict[str, Array], Array]:
    """selection_layer_stats for the (vals, idx) wire form, without ever
    densifying the selection: the selected coordinates' layer ids are a
    gather ``seg[idx]``, and every per-layer stat is a k-sized segment
    reduction (k << N), plus one [N] reduction for the per-layer acc
    mass. A value-0 slot counts as not sent (padding convention)."""
    mask = vals != 0
    seg_sel = jnp.take(jnp.asarray(seg), idx, mode="clip")
    sent = jax.ops.segment_sum(
        mask.astype(jnp.float32), seg_sel, num_segments=L)
    tau = jax.ops.segment_min(
        jnp.where(mask, jnp.abs(vals), jnp.inf), seg_sel, num_segments=L)
    tau = jnp.where(jnp.isfinite(tau), tau, 0.0).astype(jnp.float32)
    acc32 = acc.astype(jnp.float32)
    v32 = vals.astype(jnp.float32)
    acc_sq = jax.ops.segment_sum(
        acc32 * acc32, seg, num_segments=L, indices_are_sorted=True)
    sel_sq = jax.ops.segment_sum(v32 * v32, seg_sel, num_segments=L)
    m_k = sel_sq / jnp.maximum(acc_sq, _MASS_EPS)
    whole = jnp.sum(sel_sq) / jnp.maximum(jnp.sum(acc_sq), _MASS_EPS)
    return {"sent": sent, "tau": tau, "m_k": m_k}, whole


def leafwise_selection_stats(
    accs: Sequence[Array], sel_denses: Sequence[Array]
) -> Tuple[Dict[str, Array], Array]:
    """Per-leaf counterpart of selection_layer_stats for the layerwise
    mode, where the flat [N] vector never exists: one small reduction per
    leaf, stacked to [L]."""
    sents, taus, sel_sqs, acc_sqs = [], [], [], []
    for a, s in zip(accs, sel_denses):
        mask = s != 0
        sents.append(jnp.sum(mask.astype(jnp.float32)))
        t = jnp.min(jnp.where(mask, jnp.abs(s), jnp.inf))
        taus.append(jnp.where(jnp.any(mask), t, 0.0).astype(jnp.float32))
        a32, s32 = a.astype(jnp.float32), s.astype(jnp.float32)
        acc_sqs.append(jnp.sum(a32 * a32))
        sel_sqs.append(jnp.sum(s32 * s32))
    sel_sq = jnp.stack(sel_sqs)
    acc_sq = jnp.stack(acc_sqs)
    whole = jnp.sum(sel_sq) / jnp.maximum(jnp.sum(acc_sq), _MASS_EPS)
    return {
        "sent": jnp.stack(sents),
        "tau": jnp.stack(taus),
        "m_k": sel_sq / jnp.maximum(acc_sq, _MASS_EPS),
    }, whole


def leafwise_sparse_selection_stats(
    accs: Sequence[Array], vals_list: Sequence[Array]
) -> Tuple[Dict[str, Array], Array]:
    """Per-leaf stats from each leaf's selected VALUES (layerwise p>1
    path, where selection is already per leaf): no scatter needed, one
    k_l-sized reduction per leaf plus the leaf's acc mass."""
    sents, taus, sel_sqs, acc_sqs = [], [], [], []
    for a, v in zip(accs, vals_list):
        mask = v != 0
        sents.append(jnp.sum(mask.astype(jnp.float32)))
        t = jnp.min(jnp.where(mask, jnp.abs(v), jnp.inf))
        taus.append(jnp.where(jnp.any(mask), t, 0.0).astype(jnp.float32))
        a32, v32 = a.astype(jnp.float32), v.astype(jnp.float32)
        acc_sqs.append(jnp.sum(a32 * a32))
        sel_sqs.append(jnp.sum(v32 * v32))
    sel_sq = jnp.stack(sel_sqs)
    acc_sq = jnp.stack(acc_sqs)
    whole = jnp.sum(sel_sq) / jnp.maximum(jnp.sum(acc_sq), _MASS_EPS)
    return {
        "sent": jnp.stack(sents),
        "tau": jnp.stack(taus),
        "m_k": sel_sq / jnp.maximum(acc_sq, _MASS_EPS),
    }, whole


def bucketed_sparse_selection_stats(
    accs: Sequence[Array], vals_list: Sequence[Array],
    idx_list: Sequence[Array], leaf_sizes: Sequence[int],
    boundaries: Sequence[int],
) -> Tuple[Dict[str, Array], Array]:
    """Per-LEAF stats recovered from bucket-concatenated selections.

    The bucketed layerwise path selects per BUCKET (one (vals, idx) set
    in each bucket's local index space), but --obs-layers reports per
    leaf. Leaf identity inside a bucket is static structure: bucket b
    covers leaves ``boundaries[b]:boundaries[b+1]``, so its local
    coordinate->leaf map is ``segment_ids(leaf_sizes[lo:hi]) + lo`` and
    each bucket's stats are one sparse_selection_layer_stats call over
    the GLOBAL leaf axis. Buckets partition the leaves, so summing the
    per-bucket [L] arrays (each zero outside its own leaf range —
    including tau, where segment_min over an empty segment reports 0)
    recovers exactly the per-leaf stats the unbucketed path computes."""
    L = len(leaf_sizes)
    out: Dict[str, Array] = {}
    for b, (a, v, i) in enumerate(zip(accs, vals_list, idx_list)):
        lo, hi = int(boundaries[b]), int(boundaries[b + 1])
        seg = segment_ids(leaf_sizes[lo:hi]) + np.int32(lo)
        stats, _ = sparse_selection_layer_stats(a, v, i, seg, L)
        out = (stats if not out
               else {key: out[key] + stats[key] for key in out})
    return out, mass_ratio(accs, vals_list)


def dense_phase_selection_stats(
    sizes: Sequence[int],
) -> Tuple[Dict[str, Array], Array]:
    """The dense (no-compression) phase's trivial selection stats:
    everything ships, so density 1 per layer, no threshold, full mass
    capture. Used by the dense mode and the warm-up dense branch so both
    lax.cond arms return an identical structure."""
    L = len(sizes)
    return {
        "sent": jnp.asarray(np.asarray(sizes, np.float32)),
        "tau": jnp.zeros((L,), jnp.float32),
        "m_k": jnp.ones((L,), jnp.float32),
    }, jnp.float32(1.0)


def update_age(age, delivered):
    """Residual-age recursion: a coordinate's age resets to 0 the step it
    ships (appears in the applied dense update) and grows by 1 otherwise.
    ``delivered`` is derived from the globally-reduced update, which is
    replicated across the mesh, so the age buffer stays replicated without
    any collective. Works leaf-wise (tree.map) for the layerwise layout.
    Caveat: exact cross-device cancellation of a shipped coordinate reads
    as not-delivered — an epsilon case on real gradients."""
    return jax.tree.map(
        lambda a, d: jnp.where(d, 0.0, a + 1.0), age, delivered)


def layer_age_means(age, seg: np.ndarray = None, L: int = 0,
                    sizes: Sequence[int] = ()) -> Array:
    """Mean residual age per layer: flat [N] buffer via one segment_sum,
    per-leaf tuple via per-leaf means."""
    if isinstance(age, tuple):
        return jnp.stack([jnp.mean(a) for a in age])
    total = jax.ops.segment_sum(
        age, seg, num_segments=L, indices_are_sorted=True)
    return total / jnp.asarray(np.maximum(np.asarray(sizes, np.float64), 1)
                               .astype(np.float32))


def assemble_layer_telemetry(
    *,
    sel_stats: Dict[str, Array],
    sizes: Sequence[int],
    grad_norm_pre_l: Array,
    grad_norm_post_l: Array,
    residual_norm_l: Array,
    age,
    seg: np.ndarray = None,
) -> Dict[str, Array]:
    """Glue the branch-dependent selection stats and the branch-independent
    norms/ages into the LAYER_FIELDS dict carried in state.telemetry."""
    L = len(sizes)
    sizes_f = jnp.asarray(np.maximum(np.asarray(sizes, np.float64), 1)
                          .astype(np.float32))
    return {
        "density": sel_stats["sent"] / sizes_f,
        "tau": sel_stats["tau"],
        "grad_norm_pre": grad_norm_pre_l,
        "grad_norm_post": grad_norm_post_l,
        "residual_norm": residual_norm_l,
        "residual_age": layer_age_means(age, seg=seg, L=L, sizes=sizes),
        "m_k": sel_stats["m_k"],
    }


def topk_recall(hits: Array, exact_vals: Array) -> Array:
    """Recall of the production selection against the exact top-k ground
    truth: fraction of exact-top-k elements (zero-padding slots excluded)
    the production path also selected. ``hits`` is bool[k] membership of
    the exact indices in the selected set."""
    real = jnp.abs(exact_vals) > 0
    n_real = jnp.maximum(jnp.sum(real.astype(jnp.float32)), 1.0)
    return jnp.sum((hits & real).astype(jnp.float32)) / n_real


def telemetry_scalars(telemetry: Dict[str, Array]) -> Dict[str, float]:
    """Host floats of the SCALAR counters in a state's telemetry dict —
    the per-layer "layers" sub-dict and the [N] "age" buffer excluded.
    One sync point shared by the trainer's "obs" record and the anomaly
    monitor, so adding a consumer never adds a device read."""
    return {
        key: float(val) for key, val in telemetry.items()
        if key not in ("layers", "age")
    }
