"""On-device training-health counters for the compression pipeline.

Everything here is traced INSIDE the jitted train step (pure jnp on
device-resident arrays — no host round-trips) and carried out through the
optimizer state's ``telemetry`` field, so the per-step numbers ride the
existing metrics path for every mode (gtopk, gtopk_layerwise, gtopk_hier,
allgather, dense).

The counter set is the paper's own analysis axis plus the residual
dynamics arXiv:1911.08772 shows convergence hinges on:

  grad_norm_pre    — L2 of the local gradient entering the pipeline
                     (post-clip, post ICI slice-sum in hier mode)
  grad_norm_post   — L2 of the averaged dense update actually applied
  residual_norm    — L2 of the error-feedback residual AFTER repair (the
                     v buffer under momentum correction)
  tau              — the top-k selection threshold: smallest selected
                     magnitude (0 in dense phases/modes)
  sent_elems       — actual NONZERO elements in the communicated set
                     (padding slots in a <k-nonzero step don't count)
  achieved_density — sent_elems / N vs. the requested rho
  wire_bytes       — the comm-volume model for this step's collective
                     (parallel.comm_bytes_per_step — O(k log P) gtopk,
                     O(k P) allgather, O(N) dense), a static per-step
                     constant that makes jsonl rows self-describing

All values are f32 scalars; under shard_map the optimizer pmeans them over
the dp axis so the stored telemetry is replicated (per-device quantities
like the residual norm become axis means, which is the number you want on
a dashboard anyway).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from gtopkssgd_tpu.parallel import comm_bytes_per_step

Array = jax.Array

TELEMETRY_FIELDS = (
    "grad_norm_pre",
    "grad_norm_post",
    "residual_norm",
    "tau",
    "sent_elems",
    "achieved_density",
    "wire_bytes",
)


def zero_telemetry() -> Dict[str, Array]:
    """The fixed telemetry structure at init (all zeros). init_fn uses this
    so the state pytree has an identical treedef at step 0 and step k."""
    return {f: jnp.zeros((), jnp.float32) for f in TELEMETRY_FIELDS}


def tree_l2(tree) -> Array:
    """L2 norm over every leaf of a pytree (flat arrays, per-leaf tuples,
    or a single array alike). Empty trees / zero-size leaves give 0."""
    leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "size")]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    total = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(total)


def residual_l2(residual) -> Array:
    """L2 of the error-feedback buffer. Under momentum correction the
    residual field is ``{"v": ..., "u": ...}``; v is the accumulated-
    velocity buffer that plays the residual's role (optimizer.py), so the
    norm reads v only — including u would double-count momentum mass."""
    if isinstance(residual, dict) and "v" in residual:
        residual = residual["v"]
    return tree_l2(residual)


def selected_tau(vals: Array) -> Array:
    """Top-k threshold from a selected-values buffer: the smallest NONZERO
    selected magnitude. Selection kernels pad value slots with 0.0 when
    fewer than k nonzeros exist; a plain min would report tau=0 for every
    such step and hide the real threshold."""
    mags = jnp.abs(vals)
    nz = mags > 0
    t = jnp.min(jnp.where(nz, mags, jnp.inf))
    return jnp.where(jnp.any(nz), t, 0.0).astype(jnp.float32)


def keep_tau(keep: Array, acc: Array) -> Array:
    """tau for the mask-form selection (compress_by_threshold): smallest
    kept magnitude, 0 when nothing is kept."""
    mags = jnp.abs(acc)
    t = jnp.min(jnp.where(keep, mags, jnp.inf))
    return jnp.where(jnp.any(keep), t, 0.0).astype(jnp.float32)


def sent_count(vals: Array) -> Array:
    """Actual nonzeros in a communicated value buffer (f32 scalar)."""
    return jnp.sum((vals != 0).astype(jnp.float32))


def make_telemetry(
    *,
    n: int,
    k: int,
    p: int,
    mode,
    ici_size: int = 1,
    grad_norm_pre,
    grad_norm_post,
    residual_norm,
    tau,
    sent_elems,
) -> Dict[str, Array]:
    """Assemble the per-step telemetry dict (all f32 scalars).

    ``n``/``k``/``p``/``mode``/``ici_size`` are static trace-time values;
    ``wire_bytes`` therefore folds to a constant — the model volume for
    this step's collective from the one shared definition
    (parallel.comm_bytes_per_step), so the metric can never drift from
    the benchmark's comm model."""
    sent = jnp.asarray(sent_elems, jnp.float32)
    return {
        "grad_norm_pre": jnp.asarray(grad_norm_pre, jnp.float32),
        "grad_norm_post": jnp.asarray(grad_norm_post, jnp.float32),
        "residual_norm": jnp.asarray(residual_norm, jnp.float32),
        "tau": jnp.asarray(tau, jnp.float32),
        "sent_elems": sent,
        "achieved_density": sent / jnp.float32(max(1, n)),
        "wire_bytes": jnp.float32(
            comm_bytes_per_step(mode, n, k, p, ici_size=ici_size)
        ),
    }
