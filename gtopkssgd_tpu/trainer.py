"""Trainer (reference L3: dl_trainer.py::DLTrainer) — builds model, data,
and optimizer from flag-equivalent config, owns the jitted SPMD train step,
the eval loops, LR schedules, gradient accumulation, and checkpointing.

Reference parity map (SURVEY.md C1):
  DLTrainer(dnn, dataset, batch_size, ...)  -> Trainer(TrainConfig(...))
  .train(n_iters)                           -> .train(n_iters)
  .test()                                   -> .test()
  per-dataset LR step schedules             -> _lr_schedule()
  grad accumulation (nsteps_update)         -> micro-batch lax.scan in-step
  checkpoint save (params only, rank 0)     -> Orbax save of FULL TrainState
                                               (params, batch_stats, opt
                                               state incl. residual, step)

TPU-native redesign: the reference runs P processes each owning one GPU and
a background comm thread; here ONE process traces ONE SPMD train step over
the whole `dp` mesh axis. The global batch is assembled host-side as
[P, B, ...] (per-rank shards from the same DataPartitioner semantics) and
sharded over the axis; compression + the gtopk collective run inside the
step via the optimizer transform; BatchNorm running stats are pmean'd so
the replicated state stays bit-identical (the reference let per-rank stats
drift and checkpointed rank 0's).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu import native
from gtopkssgd_tpu.data import get_dataset
from gtopkssgd_tpu.data.cifar import CIFAR_MEAN, CIFAR_STD
from gtopkssgd_tpu.data.imagenet import IMAGENET_MEAN, IMAGENET_STD
from gtopkssgd_tpu.models import get_model
from gtopkssgd_tpu.optimizer import (
    GTopKSGDState,
    expand_residual_per_device,
    gtopk_sgd,
)
from gtopkssgd_tpu.obs import (
    AnomalyMonitor,
    StallWatchdog,
    Thresholds,
    TimelineRecorder,
    Tracer,
    layer_names,
    telemetry_scalars,
)
from gtopkssgd_tpu.obs.manifest import config_hash, run_manifest
from gtopkssgd_tpu.obs.watchdog import _default_on_stall
from gtopkssgd_tpu.parallel import make_mesh
from gtopkssgd_tpu.utils import (
    CheckpointManager,
    MetricsLogger,
    Prefetcher,
    get_logger,
    safe_donate,
)


@dataclasses.dataclass
class TrainConfig:
    """Flag set matching the reference entrypoints (SURVEY.md §5 config):
    --dnn --dataset --batch-size --lr --nworkers --density --compression
    --nsteps-update --data-dir --max-epochs, plus TPU-specific knobs."""

    dnn: str = "resnet20"
    dataset: Optional[str] = None  # default: the model's canonical dataset
    batch_size: int = 32           # per-worker (global = batch_size*nworkers)
    lr: Optional[float] = None     # default per dataset
    momentum: float = 0.9
    weight_decay: Optional[float] = None  # default per dataset
    nesterov: bool = False
    compression: Optional[str] = None     # None/'dense'|'gtopk'|'allgather'
                                          # |'gtopk_hier' (TPU extension)
    density: float = 0.001
    hier_ici: int = 1              # gtopk_hier: devices per ICI slice (dense
                                   # psum within, gtopk across slices)
    topk_method: str = "auto"
    wire_codec: str = "fp32"       # on-wire sparse-set encoding for every
                                   # exchange round (parallel.codec grammar:
                                   # fp32 | int8[:BLOCK] | fp8[:BLOCK])
    comm_plan: str = "auto"        # wire-plan pin (parallel.planner):
                                   # 'auto' scores candidates with the
                                   # alpha-beta model; a plan name (tree |
                                   # balanced | allgather | hier | dense)
                                   # pins the schedule for this mode
    buckets: str = "concat"        # gtopk_layerwise only: gradient
                                   # bucketing (parallel.bucketing grammar:
                                   # concat | leaf | auto | an int B).
                                   # 'concat' = historical single-merge
                                   # wire; 'leaf' = one merge per param
                                   # leaf; 'auto'/B = alpha-beta-optimal
                                   # byte-balanced contiguous buckets
    pipeline: str = "serial"       # bucketed layerwise only: bucket
                                   # execution order (modes.PIPELINES +
                                   # 'auto'). 'serial' = the paper's
                                   # sequential select->merge chain;
                                   # 'overlap' = double-buffered stages
                                   # (bucket b+1's selection runs under
                                   # bucket b's merge), bit-identical;
                                   # 'auto' = cheaper modeled span wins
    clip_grad_norm: Optional[float] = None  # default: LSTMs clip (ref §3.4)
    nsteps_update: int = 1
    warmup_epochs: int = 0         # linear LR ramp over the first N epochs
                                   # (large-batch warm-up, Goyal-style)
    dense_warmup_epochs: int = 0   # sparse modes: communicate DENSE for the
                                   # first N epochs, then switch to top-k
                                   # (reference C6 warm-up trick / DGC
                                   # warm-up training, arXiv:1712.01887)
    momentum_correction: bool = False  # sparse modes: DGC momentum
                                   # correction + factor masking (velocity
                                   # accumulates BEFORE selection;
                                   # arXiv:1712.01887 §3, TPU extension)
    restore_rejected_u: bool = False   # ABLATION ONLY: the rejected-pick
                                   # velocity-restore semantics measured
                                   # (and rejected) in warmup_ab's
                                   # restore_rejected_u_ablation entry
    max_epochs: int = 140
    nworkers: int = 1
    data_dir: Optional[str] = None
    out_dir: Optional[str] = None
    seed: int = 42
    dtype: str = "float32"         # compute dtype: 'float32' | 'bfloat16'
    space_to_depth: bool = False   # resnet50: MXU-friendly s2d stem (same
                                   # linear map as the 7x7/2 conv; see
                                   # models/resnet.py and the equivalence
                                   # test)
    eval_batches: Optional[int] = None   # cap eval batches (None = full)
    synth_hard: bool = False       # synthetic CIFAR only: the
                                   # discriminative variant (weak spatial
                                   # class patterns + 10% train label
                                   # noise) — see data/cifar.py::_synthetic;
                                   # no effect with real data present
    log_interval: int = 50
    obs_counters: bool = True      # on-device training-health counters
                                   # (obs.counters: achieved density, tau,
                                   # grad/residual norms, wire bytes)
                                   # computed inside the jitted step and
                                   # logged as "obs" records; off -> the
                                   # step traces identically to pre-obs
    obs_interval: int = 1          # log an "obs" record every N optimizer
                                   # steps. Reading the counters blocks on
                                   # the dispatched step, so raise this to
                                   # keep async dispatch overlap on real
                                   # accelerators (CPU-mesh runs are
                                   # synchronous anyway)
    obs_layers: bool = False       # per-layer compression-quality
                                   # telemetry (obs.counters.LAYER_FIELDS:
                                   # density, tau, grad/residual norms,
                                   # mean residual age, mass-capture
                                   # m(k)), logged as one "layers" record
                                   # per layer per obs step. Opt-in: it
                                   # adds [L]-sized state (a treedef
                                   # change checkpoints from default runs
                                   # would not restore into) and a few
                                   # segment reductions to the step.
                                   # Requires obs_counters.
    obs_audit_interval: int = 0    # every N optimizer steps, audit the
                                   # production top-k selection against
                                   # the exact top-k of the accumulator
                                   # (ops.topk exact path as ground
                                   # truth); recall lands in the "obs"
                                   # record's audit_recall (-1 = never
                                   # audited). 0 disables. Requires
                                   # obs_counters.
    obs_watchdog: float = 0.0      # seconds a dispatched step may go
                                   # without host-visible progress before
                                   # the stall watchdog dumps a diagnostic
                                   # and fails fast (obs.watchdog, exit
                                   # code 43); 0 disables. Set it well
                                   # above log_interval * step_time: the
                                   # heartbeat fires on blocking reads
                                   # (obs/log records, the end-of-train
                                   # sync), not on async enqueues
    obs_events: bool = True        # online anomaly monitor (obs.events)
                                   # over the synced loss/telemetry:
                                   # NaN/Inf loss, EWMA loss spike,
                                   # density collapse vs rho, residual
                                   # blow-up/age runaway — severity-
                                   # tagged "event" records, fsync'd.
                                   # Piggybacks on reads the loop already
                                   # does (obs/log intervals); never adds
                                   # a device sync.
    obs_halt_on: Optional[str] = None  # "error" | "warn": raise
                                   # AnomalyHalt (dist_trainer exit 44)
                                   # when an event of at least this
                                   # severity fires; None = record only
    obs_timeline: Optional[str] = None  # write the host-side Chrome-
                                   # trace timeline (obs.timeline: Tracer
                                   # spans, telemetry counter tracks,
                                   # event/stall markers) here on exit
                                   # (a directory gets timeline.json
                                   # appended); None disables
    obs_export_port: int = 0       # serve the latest metric values as
                                   # OpenMetrics text on this localhost
                                   # HTTP port (obs.exporter; curl
                                   # localhost:PORT/metrics). -1 binds an
                                   # ephemeral port (tests); 0 disables.
                                   # Every process exports — scrape each
                                   # host for its own rank's view
    inject: Optional[str] = None   # step-keyed fault injection spec
                                   # (resilience/inject.py grammar:
                                   # KIND[:ARG...]@STEP|A-B|latest,
                                   # comma-separated — e.g.
                                   # "nan_grad@120,preempt@200");
                                   # deterministic, so chaos runs
                                   # reproduce in CI. None disables
    recover_policy: Optional[str] = None  # map anomaly rules to
                                   # recovery actions instead of exit
                                   # 44 (resilience/policy.py grammar:
                                   # rule=action[:budget[:param]] —
                                   # e.g. "nan_loss=skip,
                                   # density_collapse=degrade:2:100").
                                   # Requires obs_events. None = halt
                                   # semantics unchanged
    allow_ckpt_mismatch: bool = False  # restore a checkpoint whose
                                   # recorded config_hash/state digest
                                   # disagrees with this run's (the
                                   # explicit escape hatch; normally a
                                   # mismatched resume is refused)
    elastic: bool = False          # elastic fleet (resilience/
                                   # elastic.py): membership changes
                                   # (preemption, eviction, injected
                                   # resize@K:NEWP) drain + save +
                                   # rewrite the elastic.json lineage
                                   # + exit 46 for a relaunch at the
                                   # new P; resume re-partitions the
                                   # dp-sharded residual onto the new
                                   # mesh. BOTH sides of a resize must
                                   # run with elastic on (the ckpt
                                   # config_hash nulls nworkers only
                                   # under this flag)
    evict_after_windows: int = 3   # elastic: self-check the fleet's
                                   # merged goodput/straggler view
                                   # every this-many obs_goodput
                                   # windows and evict the rank
                                   # eviction_decision names (0
                                   # disables the automatic check;
                                   # injected evict_rank still works)
    min_fleet: int = 1             # elastic: never resize below this
                                   # many workers (an eviction or
                                   # shrink that would is refused and
                                   # degrades to preempt semantics)
    prefetch: int = 2              # host batches assembled ahead by a
                                   # background thread (0 = synchronous;
                                   # reference C8 parity with DataLoader
                                   # worker overlap)
    decode_workers: int = 0        # ImageNet real-file path: decode worker
                                   # processes (reference DataLoader
                                   # num_workers; one host core decodes
                                   # ~280 img/s vs the ~6.8k img/s a v5e
                                   # chip eats at bs=128 — input_path
                                   # artifact)
    steps_per_dispatch: int = 1    # optimizer steps per jitted dispatch:
                                   # >1 stages that many host batches and
                                   # lax.scan's the train step on-device,
                                   # amortizing per-step dispatch cost.
                                   # Pays only where dispatch DOMINATES —
                                   # ms-scale steps on a real chip (a v5e
                                   # runs ResNet-20-sized steps at 100s
                                   # of dispatches/sec); measured NEUTRAL
                                   # on the CPU meshes (steps are
                                   # seconds: 5.5 vs 6.0 s/step at
                                   # mesh8, 6.5 vs 7.5 at mesh2 — host
                                   # overhead never dominates there).
                                   # Semantics identical to
                                   # steps_per_dispatch=1 (per-step RNG,
                                   # warm-up cond, BPTT carry all thread
                                   # through the scan; equality
                                   # test-pinned); train() reports the
                                   # dispatch's last-step loss, same as
                                   # the per-step path reports its last
                                   # step. num_iters must divide.
    obs_calib: bool = False        # live comm-model calibration
                                   # (obs/calib.py): profile-attribute a
                                   # dispatch every obs_calib_interval
                                   # steps, feed measured (wire_bytes,
                                   # t_comm) to an online alpha/beta
                                   # fitter; "calib" records per refit,
                                   # comm_model_drift rule vs the
                                   # planner's inputs, end-of-run
                                   # calib_fit_{P}proc.json artifact in
                                   # out_dir. Needs obs_counters and
                                   # nworkers > 1; off by default — each
                                   # measurement is a profiler capture
    obs_calib_interval: int = 25   # steps between calibration captures
    obs_critpath: bool = False     # per-step stage-interval records
                                   # (obs/critpath.py): profile-attribute
                                   # a dispatch every obs_calib_interval
                                   # steps (shares the calibrator's
                                   # capture when both are on) and log a
                                   # durable "critpath" record — ordered
                                   # {stage, t0, t1} segments with the
                                   # comm span wait-split against the
                                   # ledger-modeled wire time — feeding
                                   # the fleet's global critical-path
                                   # join and the critpath_shift rule
    obs_critpath_shift_windows: int = 3  # consecutive joined steps whose
                                   # global critical stage differs from
                                   # the modal one before critpath_shift
                                   # fires (obs.events.Thresholds)
    registry: Optional[str] = None  # append this run's summary line to
                                   # DIR/runs.jsonl on exit
                                   # (obs/registry.py; read back with
                                   # `report history` / `report
                                   # regress`). None disables
    comm_model_fit: Optional[str] = None  # explicit alpha/beta fit
                                   # artifact (dcn_probe_*.json or
                                   # calib_fit_*.json) pricing the comm
                                   # planner, overriding the probe-dir
                                   # lookup; the filename is stamped as
                                   # fit provenance in manifest + plan
                                   # record. Malformed file fails at
                                   # startup. None = default lookup
    obs_mem: bool = False          # compile/memory-plane watch
                                   # (obs/memwatch.py): AOT compile
                                   # accounting — one fsync'd "compile"
                                   # record per distinct dispatch shape
                                   # (cost/memory analysis, lower/
                                   # compile wall times) with the
                                   # peak-HBM estimate stamped into the
                                   # manifest — plus the jit-cache
                                   # recompile watch (recompile_storm
                                   # rule) and sampled live-memory
                                   # "mem" records feeding the
                                   # device_mem_leak / hbm_headroom
                                   # rules. Costs one AOT compile per
                                   # distinct dispatch shape
    obs_mem_interval: int = 50     # steps between live-memory samples
                                   # (jax.live_arrays + memory_stats
                                   # reads are host-side but not free);
                                   # samples land at sync points the
                                   # loop already pays
    obs_recompile_warmup: int = 1  # compile-watch polls before the
                                   # recompile_storm rule arms; 0 means
                                   # ANY executable-cache growth fires
                                   # (obs.events.Thresholds)
    obs_mem_leak_windows: int = 3  # consecutive growing live-bytes
                                   # windows before device_mem_leak
                                   # fires (a plateau resets the streak)
    obs_hbm_headroom_frac: float = 0.92  # bytes_in_use / bytes_limit
                                   # fraction above which hbm_headroom
                                   # fires (backends without
                                   # memory_stats never arm it)
    obs_goodput: bool = True       # goodput/badput wall-clock ledger
                                   # (obs/goodput.py): partition the
                                   # run's measured wall into productive
                                   # step compute vs the badput taxonomy
                                   # (select/comm/wait/compile/ckpt/
                                   # wasted/degraded/data/startup), with
                                   # the unattributed remainder surfaced
                                   # as other_frac (conservation). Pure
                                   # host arithmetic at sync points the
                                   # loop already pays — on by default.
                                   # One durable cumulative "goodput"
                                   # record every obs_goodput_interval
                                   # steps + an end-of-run summary
    obs_goodput_interval: int = 50  # optimizer steps between periodic
                                   # durable "goodput" records (<= 0
                                   # keeps only the end-of-run summary);
                                   # each record also feeds the
                                   # goodput_collapse rule
    obs_goodput_collapse_windows: int = 3  # consecutive ledger records
                                   # with goodput_frac below half its
                                   # EWMA before goodput_collapse fires
                                   # (obs.events.Thresholds)
    obs_linkmap: bool = False      # per-(axis, peer) network weather
                                   # map (obs/linkmap.py): carve each
                                   # calibration capture's measured comm
                                   # span over the schedule's
                                   # round->peer join, keep EWMA
                                   # latency/bandwidth per link, log a
                                   # durable "linkmap" record per
                                   # capture, feed the link_degraded
                                   # rule. Rides the calibrator cadence,
                                   # so it implies the same capture cost
                                   # as obs_calib
    obs_link_degraded_x: float = 4.0  # one link's EWMA latency over
                                   # the fleet median by this factor
                                   # counts as a degraded window
                                   # (obs.events.Thresholds)
    obs_link_degraded_windows: int = 3  # consecutive degraded windows
                                   # before link_degraded fires; a
                                   # recovered window re-arms
                                   # (obs.events.Thresholds)
    obs_forecast: bool = False     # scale-out forecast plane
                                   # (obs/forecast.py): hindcast the
                                   # analytic step model against THIS
                                   # run each calibration capture, then
                                   # forecast step time / goodput at
                                   # the P targets across schedules and
                                   # axis trees. One durable "forecast"
                                   # record per capture; feeds the
                                   # forecast_drift rule. Requires
                                   # obs_calib (rides its cadence)
    obs_forecast_targets: str = "32,256,1024"  # comma-separated modeled
                                   # worker counts the forecast grid
                                   # prices (ROADMAP item 3 evidence
                                   # targets)
    obs_forecast_drift_x: float = 4.0  # hindcast error factor beyond
                                   # which a capture counts as drifted;
                                   # 3 consecutive drifted captures
                                   # fire forecast_drift
                                   # (obs.events.Thresholds)

    # --- per-dataset defaults (the reference hardcoded these in DLTrainer) --
    def resolved(self) -> "TrainConfig":
        cfg = dataclasses.replace(self)
        if cfg.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch={cfg.steps_per_dispatch} must be "
                ">= 1")
        if cfg.dataset is None:
            from gtopkssgd_tpu.models import get_model as _gm
            cfg.dataset = _gm(cfg.dnn)[1].dataset
        defaults = {
            # dataset: (lr, weight_decay, clip)
            "cifar10": (0.1, 5e-4, None),
            "imagenet": (0.01 if cfg.dnn == "alexnet" else 0.1, 1e-4, None),
            "ptb": (1.0, 0.0, 0.25),
            "an4": (3e-4, 0.0, 400.0),
        }
        lr, wd, clip = defaults.get(cfg.dataset, (0.1, 0.0, None))
        if cfg.lr is None:
            cfg.lr = lr
        if cfg.weight_decay is None:
            cfg.weight_decay = wd
        if cfg.clip_grad_norm is None:
            cfg.clip_grad_norm = clip
        return cfg


# Per-dataset normalization constants for the uint8 wire format: pipelines
# ship raw pixels, the jitted step normalizes on device.
_WIRE_STATS = {
    "cifar10": (CIFAR_MEAN, CIFAR_STD),
    "imagenet": (IMAGENET_MEAN, IMAGENET_STD),
}


class TrainState(NamedTuple):
    """The whole checkpointable training state, one pytree. Residual lives
    inside opt_state (GTopKSGDState), so resume preserves error feedback."""

    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any


def shard_steps_per_epoch(ds, batch_size: int, nsteps_update: int = 1) -> int:
    """Optimizer steps per epoch for a rank's dataset shard.

    Must be identical on EVERY process of a multi-host run (each step
    issues collectives; disagreement desyncs the SPMD program). The
    partitioner gives the last rank the dataset remainder, so the count is
    derived from the MINIMUM shard size — a pure function of
    (n, nworkers, batch_size) every process agrees on — rather than from
    whichever shard happens to be local. Shared by the Trainer and the
    convergence runner so max_epochs-from-steps arithmetic cannot drift
    from the LR schedule's epoch length."""
    spe = ds.steps_per_epoch()
    part = getattr(ds, "partitioner", None)
    if part is not None and part.nworkers > 1:
        spe = (part.n // part.nworkers) // batch_size
    return max(1, spe // nsteps_update)


class Trainer:
    def __init__(self, config: TrainConfig):
        self.cfg = cfg = config.resolved()
        self.process_rank = jax.process_index()
        self.logger = get_logger("trainer", rank=self.process_rank)
        # Live OpenMetrics endpoint (obs.exporter): fed as the metrics
        # sink so it sees exactly the records this rank produces, file
        # or no file. Started before the logger so the sink exists.
        self.exporter = None
        if cfg.obs_export_port:
            from gtopkssgd_tpu.obs.exporter import MetricsExporter

            port = max(0, cfg.obs_export_port)
            self.exporter = MetricsExporter(port=port).start()
            self.logger.info(
                "obs exporter: http://127.0.0.1:%d/metrics",
                self.exporter.port)
        # Multi-process runs shard per rank (metrics.rank{r}.jsonl) so
        # the fleet merger (obs/fleet.py) has per-host streams to align;
        # single-process keeps the classic metrics.jsonl.
        self.metrics = MetricsLogger(
            cfg.out_dir, self.logger, rank=self.process_rank,
            shard=jax.process_count() > 1,
            sink=self.exporter.observe if self.exporter else None)
        # Goodput/badput ledger (obs/goodput.py): constructed FIRST so
        # its wall-clock t0 covers the whole init (model/data/compile
        # all land in startup/compile, not in a blind spot). The monitor
        # is attached below once it exists.
        self.goodput = None
        if cfg.obs_goodput:
            from gtopkssgd_tpu.obs.goodput import GoodputLedger
            self.goodput = GoodputLedger(
                metrics=self.metrics,
                interval=cfg.obs_goodput_interval)
        # Host timeline (obs.timeline): spans + telemetry tracks + event
        # markers as one chrome-trace JSON, written on __exit__ (and
        # best-effort on a watchdog stall). Rank 0 only, like metrics.
        self.timeline = (
            TimelineRecorder(rank=self.process_rank)
            if cfg.obs_timeline and self.process_rank == 0 else None
        )
        # Span tracer (obs.tracing): host phase timing + profiler
        # TraceAnnotations under one name. Replaces the bare StepTimer
        # (utils/timers.py keeps the primitive).
        self.tracer = Tracer(
            metrics=self.metrics,
            sink=self.timeline.span_sink if self.timeline else None,
        )
        # Online anomaly monitor (obs.events): fed at the obs/log sync
        # points below; density rules only make sense when a sparse mode
        # has a configured rho.
        from gtopkssgd_tpu.modes import DENSE_MODES

        self.monitor = (
            AnomalyMonitor(
                metrics=self.metrics,
                rho=(cfg.density
                     if cfg.compression not in DENSE_MODES else None),
                halt_on=cfg.obs_halt_on,
                thresholds=Thresholds(
                    recompile_warmup=cfg.obs_recompile_warmup,
                    mem_leak_windows=cfg.obs_mem_leak_windows,
                    hbm_headroom_frac=cfg.obs_hbm_headroom_frac,
                    critpath_shift_windows=cfg.obs_critpath_shift_windows,
                    goodput_collapse_windows=(
                        cfg.obs_goodput_collapse_windows),
                    link_degraded_x=cfg.obs_link_degraded_x,
                    link_degraded_windows=cfg.obs_link_degraded_windows,
                    forecast_drift_x=cfg.obs_forecast_drift_x),
                timeline=self.timeline,
            )
            if cfg.obs_events else None
        )
        if self.goodput is not None:
            self.goodput.monitor = self.monitor
        self.watchdog = (
            StallWatchdog(cfg.obs_watchdog,
                          on_stall=self._on_stall,
                          diagnostics=self._stall_diagnostics)
            if cfg.obs_watchdog > 0 else None
        )
        # Resilience layer (gtopkssgd_tpu/resilience): deterministic
        # step-keyed fault injection, and the recovery manager that
        # claims monitor events before they escalate to a halt. The
        # preemption guard is NOT installed here — a library object
        # must not steal the host process's signal handlers; dist_trainer
        # (or a test) installs one and assigns it to `self.preempt`.
        from gtopkssgd_tpu.resilience import (
            FaultInjector,
            RecoveryManager,
            parse_policy,
            retry_call,
        )

        self.injector = (
            FaultInjector(cfg.inject, metrics=self.metrics,
                          logger=self.logger, rank=self.process_rank)
            if cfg.inject else None
        )
        self.recovery = (
            RecoveryManager(parse_policy(cfg.recover_policy),
                            metrics=self.metrics, logger=self.logger)
            if cfg.recover_policy else None
        )
        if self.recovery is not None:
            if self.monitor is None:
                raise ValueError(
                    "recover_policy requires obs_events (recovery acts "
                    "on AnomalyMonitor events)")
            self.monitor.recovery = self.recovery.claim
        self.preempt = None

        self.model, self.spec = get_model(
            cfg.dnn,
            dtype=jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32,
            space_to_depth=cfg.space_to_depth,
        )
        self.mesh = make_mesh(cfg.nworkers)
        self.p = cfg.nworkers

        # In a multi-host run each process feeds only the mesh positions its
        # own devices occupy; make_array_from_process_local_data assembles
        # the global [P, ...] batch (single host: all ranks are local).
        self.local_ranks = [
            i for i, d in enumerate(self.mesh.devices.flat)
            if d.process_index == self.process_rank
        ]
        data_kw = dict(
            batch_size=cfg.batch_size, data_dir=cfg.data_dir, seed=cfg.seed
        )
        if cfg.dataset == "imagenet" and cfg.decode_workers > 0:
            data_kw["decode_workers"] = cfg.decode_workers
        if cfg.dataset == "cifar10" and cfg.synth_hard:
            data_kw["synth_hard"] = True
        def _dataset(**kw):
            # Data-loader setup rides the shared retry/backoff helper
            # (resilience/preempt.py): a transient storage blip at
            # startup must not kill a pod-sized run before step 1.
            return retry_call(
                functools.partial(get_dataset, cfg.dataset, **kw),
                retries=2, delay=0.5, logger=self.logger,
                desc=f"get_dataset({cfg.dataset})")

        self.train_shards = [
            _dataset(split="train", rank=r, nworkers=cfg.nworkers,
                     **data_kw)
            for r in self.local_ranks
        ]
        self.val_data = _dataset(split="test", **data_kw)
        self.steps_per_epoch = shard_steps_per_epoch(
            self.train_shards[0], cfg.batch_size, cfg.nsteps_update
        )

        # Explicit comm-model fit (--comm-model-fit): loaded once here —
        # a malformed artifact fails at startup, not mid-run. It prices
        # the plan decision below and its filename is stamped as fit
        # provenance; _comm_plan_pin later pins the optimizer's
        # trace-time resolve_plan to the decision it produced.
        self._comm_fit = None
        self._comm_plan_pin = None
        if cfg.comm_model_fit:
            from gtopkssgd_tpu.obs.calib import load_fit_file
            self._comm_fit = load_fit_file(cfg.comm_model_fit)
        self.tx = self._make_tx()
        self.state, self.carry = self._init_state()
        # Layer-name column for "layers" records: index i of every
        # telemetry [L] array is leaf i of the params pytree in jax.tree
        # flatten order — the same order the optimizer's segment map uses.
        self._layer_names = (
            layer_names(self.state.params) if cfg.obs_layers else ())
        # Wire-plan decision (parallel.planner): resolved once here with
        # the same inputs the optimizer's trace-time resolve_plan sees,
        # logged as the "plan" record (chosen plan + every candidate's
        # score) and stamped into the manifest so the ledger prices the
        # schedule that actually ran. Dense / single-device runs have no
        # sparse wire to plan.
        self._plan_decision = None
        # Bucket plan (parallel.bucketing): resolved host-side from the
        # SAME leaf sizes the optimizer's trace-time plan_buckets sees
        # (params pytree flatten order), so the manifest/"bucket" record
        # describe the boundaries that actually ran. Layerwise-only.
        self._bucket_plan = None
        if cfg.compression == "gtopk_layerwise":
            from gtopkssgd_tpu.parallel import parse_buckets, plan_buckets
            if parse_buckets(cfg.buckets) != "concat":
                leaf_sizes = tuple(
                    int(leaf.size)
                    for leaf in jax.tree_util.tree_leaves(self.state.params))
                self._bucket_plan = plan_buckets(
                    leaf_sizes, cfg.density, buckets=cfg.buckets,
                    p=self.p, codec=cfg.wire_codec,
                    pipeline=cfg.pipeline)
        if cfg.compression not in (None, "none", "dense") and self.p > 1:
            from gtopkssgd_tpu.parallel import build_decision
            from gtopkssgd_tpu.parallel.bucketing import buckets_key
            bplan = self._bucket_plan
            k = (bplan.k_total if bplan is not None
                 else max(1, int(np.ceil(cfg.density * self.num_params))))
            fit_kw = {}
            if self._comm_fit is not None:
                fit_kw = dict(alpha_ms=self._comm_fit["alpha_ms"],
                              beta_gbps=self._comm_fit["beta_gbps"],
                              fit_source=self._comm_fit["source"])
            self._plan_decision = build_decision(
                cfg.compression, p=self.p, n=self.num_params, k=k,
                codec=cfg.wire_codec, ici_size=cfg.hier_ici,
                pin=cfg.comm_plan,
                bucketing=buckets_key(cfg.buckets),
                buckets=bplan.pairs() if bplan is not None else None,
                pipeline=(bplan.pipeline if bplan is not None
                          else "serial"),
                **fit_kw)
        if (self._comm_fit is not None and self._plan_decision is not None
                and self._plan_decision.pin == "auto"):
            # The optimizer's trace-time resolve_plan only sees the
            # default probe dir; pin it to the decision the explicit fit
            # priced, or the wire that runs could disagree with the plan
            # that was recorded. Same state treedef — comm_plan never
            # shapes opt state — so the rebuilt tx drops in.
            self._comm_plan_pin = self._plan_decision.plan.name
            self.tx = self._make_tx()
        plan_extra = {}
        if self._plan_decision is not None:
            d = self._plan_decision
            plan_extra = {"comm_plan": d.plan.name,
                          "comm_plan_schedule": d.plan.schedule,
                          "comm_plan_pin": d.pin,
                          # which comm model priced this plan — the
                          # ledger/plan report headers read these back
                          "comm_fit_source": d.inputs.get("fit_source"),
                          "comm_fit_alpha_ms": d.inputs.get("alpha_ms"),
                          "comm_fit_beta_gbps": d.inputs.get("beta_gbps")}
        if self._bucket_plan is not None:
            plan_extra.update(self._bucket_plan.to_manifest())
        # Compile-plane accounting (obs/memwatch.py, --obs-mem): build
        # the jitted step and AOT lower/compile it at the canonical
        # dispatch shape BEFORE the manifest is assembled, so the
        # compile record's peak-HBM estimate rides the manifest header
        # (run_manifest's **extra). The AOT pass never executes —
        # abstract ShapeDtypeStruct batch leaves stand in for data, so
        # no batch is consumed from the stream.
        self._train_step = self._build_train_step()
        self.memwatch = None
        init_compile = None
        if cfg.obs_mem:
            from gtopkssgd_tpu.obs.memwatch import MemWatch
            self.memwatch = MemWatch(
                metrics=self.metrics, monitor=self.monitor,
                mem_interval=cfg.obs_mem_interval, logger=self.logger)
            # Ledger cursor: init-so-far is startup, the AOT pass that
            # follows is compile (train_started() later picks up the
            # rest of init as startup).
            if self.goodput is not None:
                self.goodput.mark("startup")
            init_compile = self.memwatch.account(
                self._train_step, self.state, self.carry,
                self._abstract_batch(), step=0, log=False)
            if self.goodput is not None:
                self.goodput.mark("compile")
            if self.memwatch.peak_hbm_bytes is not None:
                plan_extra["peak_hbm_bytes"] = self.memwatch.peak_hbm_bytes
        # Elastic lineage (resilience/elastic.py): one LOGICAL run =
        # one lineage_id, carried across resizes via out_dir's
        # elastic.json — adopted when the relaunch finds one, minted
        # fresh otherwise. Stamped into the manifest ONLY under
        # cfg.elastic so non-elastic manifests stay byte-stable.
        self.lineage = None
        if cfg.elastic:
            from gtopkssgd_tpu.resilience.elastic import (
                load_lineage, mint_lineage_id, write_lineage)
            self.lineage = load_lineage(cfg.out_dir)
            if self.lineage is None:
                self.lineage = {"lineage_id": mint_lineage_id(),
                                "resize_epoch": 0, "p": self.p}
                if cfg.out_dir:
                    write_lineage(cfg.out_dir, **self.lineage)
            plan_extra["lineage_id"] = self.lineage["lineage_id"]
            plan_extra["resize_epoch"] = int(
                self.lineage.get("resize_epoch", 0))
        # Run-manifest header: first record of every metrics file, so
        # each is self-describing (config hash + resolved headline flags,
        # mesh, jax/backend versions, git sha). In sharded multi-process
        # runs EVERY rank writes it — config_hash is the join key the
        # fleet merger validates before aligning shards.
        self._manifest = run_manifest(
            cfg, mesh=self.mesh, num_params=self.num_params,
            steps_per_epoch=self.steps_per_epoch, **plan_extra)
        self.metrics.log("manifest", flush=True, **self._manifest)
        # The manifest stays the FIRST record; the deferred startup
        # compile record lands right after it, and the recompile watch
        # arms on the same jitted callable the loop dispatches.
        if init_compile is not None:
            self.memwatch.log_compile(init_compile)
        if self.memwatch is not None:
            self.memwatch.attach(self._train_step)
        if self._plan_decision is not None:
            self.metrics.log("plan", flush=True,
                             **self._plan_decision.record())
        if self._bucket_plan is not None:
            self.metrics.log("bucket", flush=True,
                             **self._bucket_record())
        # Live comm-model calibrator (obs/calib.py): fed measured
        # (wire_bytes, t_comm) from the profiler-attributed dispatches in
        # train(); its drift baseline is the EXACT inputs that priced
        # this run's plan. p == 1 has no wire to calibrate.
        self.calib = None
        self.linkmap = None
        self.forecaster = None
        if cfg.obs_calib and cfg.obs_counters and self.p > 1:
            from gtopkssgd_tpu.obs.calib import CommCalibrator
            d = self._plan_decision
            if d is not None:
                wire_mode = d.plan.wire_mode
                inputs = d.inputs
            else:
                from gtopkssgd_tpu.parallel.planner import planner_inputs
                wire_mode, inputs = "dense", planner_inputs(None)
            self.calib = CommCalibrator(
                wire_mode, self.p,
                baseline={key: inputs.get(key) for key in
                          ("alpha_ms", "beta_gbps", "ici_gbps",
                           "fit_source")},
                metrics=self.metrics, monitor=self.monitor,
                ici_size=cfg.hier_ici)
            # Link weather map (obs/linkmap.py): carves the SAME
            # (wire_bytes, t_comm) capture the calibrator consumes over
            # the schedule's round->peer join; rides the calib cadence,
            # so it only exists when the calibrator does.
            if cfg.obs_linkmap:
                from gtopkssgd_tpu.obs.linkmap import LinkMap
                self.linkmap = LinkMap(
                    wire_mode, self.p, rank=self.process_rank,
                    ici_size=cfg.hier_ici,
                    alpha_ms=float(inputs.get("alpha_ms") or 0.1),
                    beta_gbps=float(inputs.get("beta_gbps") or 25.0),
                    ici_gbps=float(inputs.get("ici_gbps") or 1600.0),
                    metrics=self.metrics, monitor=self.monitor)
            # Scale-out forecast plane (obs/forecast.py): the digital
            # twin hindcasts against this run and forecasts the P
            # targets, riding the same capture cadence (it consumes the
            # calibrator's refits, the weather map's snapshots, and the
            # critpath budgets the loop already produces).
            if cfg.obs_forecast:
                from gtopkssgd_tpu.obs.forecast import StepForecaster
                bplan = self._bucket_plan
                fc_k = (bplan.k_total if bplan is not None
                        else max(1, int(np.ceil(
                            cfg.density * self.num_params))))
                if cfg.compression in (None, "none", "dense"):
                    fc_k = self.num_params
                try:
                    targets = tuple(
                        int(t) for t in
                        str(cfg.obs_forecast_targets).split(",")
                        if t.strip())
                except ValueError:
                    raise ValueError(
                        "--obs-forecast-targets must be a comma-"
                        "separated list of worker counts, got "
                        f"{cfg.obs_forecast_targets!r}")
                self.forecaster = StepForecaster(
                    {"mode": cfg.compression or "dense", "p": self.p,
                     "n": self.num_params, "k": fc_k,
                     "codec": cfg.wire_codec,
                     "schedule": (d.plan.schedule
                                  if d is not None else None),
                     "bucketing": cfg.buckets or "concat",
                     "buckets": (bplan.pairs()
                                 if bplan is not None else None),
                     "ici_size": cfg.hier_ici},
                    baseline=inputs, targets=targets,
                    metrics=self.metrics, monitor=self.monitor)
        self._eval_step = self._build_eval_step()
        # Degrade fallback (recover-policy "degrade"): the sparse step
        # stays canonical; a dense-allreduce variant over the SAME
        # optimizer state treedef (warmup_dense_steps=2**30 selects the
        # dense branch of the compiled update) is built lazily on the
        # first degrade action.
        self._sparse_step = self._train_step
        self._dense_step = None
        self._degraded = False
        self._degrade_until = 0
        # Checkpoints: orbax save/restore of the live sharded state; on
        # multi-host EVERY process participates (orbax coordinates; each
        # writes its addressable residual shards) over a shared filesystem.
        # The manager stamps each save with a config_hash so a mismatched
        # resume is refused instead of silently changing the experiment —
        # computed with the resilience knobs nulled out: an injected-fault
        # run and its clean resume are the SAME experiment (the injection
        # perturbs execution, never the checkpointable state treedef), and
        # a chaos run that could not be resumed without --inject would
        # defeat the preempt/resume path it exists to test.
        nulled = dict(inject=None, recover_policy=None,
                      allow_ckpt_mismatch=False)
        if cfg.elastic:
            # A resize changes nworkers and NOTHING else about the
            # experiment, so pre- and post-resize checkpoints must
            # agree on config_hash: under --elastic the fleet size and
            # the elastic knobs are nulled too (which is why BOTH sides
            # of a resize must run with --elastic — a non-elastic
            # resume of an elastic checkpoint is refused as a
            # different experiment, by design).
            # out_dir/registry are workspace plumbing, not experiment
            # identity — and the relaunch contract puts the resumed run
            # in a FRESH out_dir (reusing the old one would corrupt its
            # registry summary), so they cannot key the ckpt hash.
            nulled.update(nworkers=0, elastic=False,
                          evict_after_windows=3, min_fleet=1,
                          out_dir=None, registry=None)
        ckpt_hash = config_hash(dataclasses.replace(cfg, **nulled))
        self._ckpt = (
            CheckpointManager(f"{cfg.out_dir}/ckpt",
                              config_hash=ckpt_hash,
                              logger=self.logger)
            if cfg.out_dir else None
        )
        self._set_iters(start_epoch=0)

    def _bucket_record(self) -> dict:
        """The "bucket" evidence record: the chosen BucketPlan's
        boundaries and per-bucket rows, plus the modeled comm ms of the
        two degenerate partitions (B=1 single merge, B=L per-leaf) so a
        report reader can see where the chosen B sits on the alpha-beta
        curve without re-running the DP."""
        from gtopkssgd_tpu.parallel import bucketing, plan_buckets
        from gtopkssgd_tpu.parallel.planner import planner_inputs
        cfg, bplan = self.cfg, self._bucket_plan
        inputs = planner_inputs(None)
        alpha, beta = inputs["alpha_ms"], inputs["beta_gbps"]
        kw = dict(p=self.p, codec=cfg.wire_codec,
                  alpha_ms=alpha, beta_gbps=beta)
        sizes = bplan.leaf_sizes

        def _ms(spec):
            alt = plan_buckets(sizes, cfg.density, buckets=spec,
                               pipeline=bplan.pipeline, **kw)
            return bucketing.partition_cost_ms(
                alt, pipeline=bplan.pipeline, **kw)

        return {
            "buckets": bplan.spec,
            "n_buckets": bplan.n_buckets,
            "n_leaves": len(sizes),
            "boundaries": list(bplan.boundaries),
            "bucket_sizes": list(bplan.sizes),
            "bucket_ks": list(bplan.ks),
            "pipeline": bplan.pipeline,
            "rows": bucketing.describe(bplan, **kw),
            "modeled_ms": bucketing.partition_cost_ms(
                bplan, pipeline=bplan.pipeline, **kw),
            "modeled_ms_b1": _ms(1),
            "modeled_ms_leaf": _ms("leaf"),
            # True wall-clock spans under both orders — the A/B a report
            # reader needs to see what pipelining bought at this B.
            "span_serial_ms": bucketing.pipeline_span_ms(
                bplan, pipeline="serial", **kw),
            "span_overlap_ms": bucketing.pipeline_span_ms(
                bplan, pipeline="overlap", **kw),
            "alpha_ms": alpha,
            "beta_gbps": beta,
        }

    def _feed_calibrator(self, step: int, spd: int,
                         trace_dir: str) -> None:
        """Attribute the just-captured dispatch and feed one measured
        (wire_bytes, t_comm_ms) sample to the comm calibrator. Wire
        bytes come from the same on-device telemetry the obs records
        read; t_comm from the profiler attribution, normalized per
        optimizer step. Attribution failure degrades to a warning — a
        missed sample must never take down training. AnomalyHalt from
        the drift rule propagates like any monitor halt."""
        import shutil

        from gtopkssgd_tpu.obs.trace_attr import attribute
        try:
            rec = attribute(trace_dir, mode=self.cfg.compression)
        except Exception as e:
            self.logger.warning("calib attribution failed: %s", e)
            return
        finally:
            shutil.rmtree(trace_dir, ignore_errors=True)
        t_comm_us = rec.get("t_comm_us")
        if not isinstance(t_comm_us, (int, float)) or t_comm_us <= 0:
            return
        tel = self.state.opt_state.telemetry
        if not tel:
            return
        wire = float(telemetry_scalars(tel).get("wire_bytes", 0.0))
        if wire <= 0:
            return
        # Overlapped dispatches measure a partially-hidden t_comm; tag
        # them so the calibrator quarantines the sample instead of
        # biasing the serial alpha-beta fit (obs/calib.py).
        overlapped = (self._bucket_plan is not None
                      and self._bucket_plan.pipeline == "overlap")
        t_comm_ms = float(t_comm_us) / 1e3 / spd
        calib_rec = self.calib.observe(step, wire_bytes=wire,
                                       t_comm_ms=t_comm_ms,
                                       overlapped=overlapped)
        lm_rec = None
        if self.linkmap is not None and not overlapped:
            # Same sample, carved per link; overlapped spans are
            # quarantined here for the same reason the calibrator
            # quarantines them — a partially-hidden t_comm would bias
            # every link's EWMA low. May raise AnomalyHalt (after its
            # durable record), like any monitor-fed surface.
            lm_rec = self.linkmap.observe(step, t_comm_ms=t_comm_ms,
                                          wire_bytes=wire)
        if self.forecaster is not None:
            # The forecast reprices itself from whatever this capture
            # refreshed: a completed refit window's fit, the weather
            # map's degradation multiple.
            if calib_rec is not None:
                self.forecaster.note_calib(calib_rec)
            if lm_rec is not None:
                self.forecaster.note_linkmap(lm_rec)

    def _log_critpath(self, step: int, spd: int, trace_dir: str,
                      cleanup: bool = True) -> None:
        """Attribute the just-captured dispatch into ordered stage
        intervals (obs/critpath.py) and log one durable "critpath"
        record. The wire budget for the wait split comes from the
        ledger's alpha-beta model priced on this run's manifest,
        scaled by spd (the capture spans spd optimizer steps); when
        the model can't parameterize, the whole comm span stays
        "comm" and no wait is claimed. Feeds the local crit_stage to
        the anomaly monitor (critpath_shift rule). ``cleanup=False``
        leaves the trace dir for the calibrator feed that follows."""
        import shutil

        from gtopkssgd_tpu.obs import critpath
        from gtopkssgd_tpu.obs.trace_attr import attribute
        try:
            w = critpath.modeled_wire_us(self._manifest, nprocs=self.p)
            rec = attribute(trace_dir, mode=self.cfg.compression,
                            stage_intervals=True,
                            wire_us=None if w is None else w * spd)
        except Exception as e:
            self.logger.warning("critpath attribution failed: %s", e)
            return
        finally:
            if cleanup:
                shutil.rmtree(trace_dir, ignore_errors=True)
        cp = rec.get("critpath")
        if not cp:
            return
        self.metrics.log("critpath", flush=True, step=step, **cp)
        if self.goodput is not None:
            # The ledger splits step time by the stage shares this
            # record just measured (compute->goodput, select/comm/wait
            # ->their badput buckets).
            self.goodput.note_stage_fracs(cp)
        if self.forecaster is not None:
            # Per-step compute/select budgets + the measured wall the
            # hindcast compares against; fed BEFORE the shift rule so
            # a halt there never starves the forecast of its budgets.
            self.forecaster.note_critpath(cp, spd=spd)
        # AnomalyHalt from the shift rule propagates like any monitor
        # halt — the durable event record lands before the raise.
        if self.monitor is not None:
            self.monitor.observe_critpath(
                step, crit_stage=cp.get("crit_stage"))

    def _make_tx(self, warmup_dense_steps: Optional[int] = None):
        """The optimizer transform; ``warmup_dense_steps`` overrides the
        config-derived value (the degrade fallback passes 2**30 to pin
        the always-dense branch — identical state treedef, so the live
        state flows between the sparse and degraded steps unchanged)."""
        cfg = self.cfg
        if warmup_dense_steps is None:
            warmup_dense_steps = (
                cfg.dense_warmup_epochs * self.steps_per_epoch)
        return gtopk_sgd(
            self._lr_schedule(),
            momentum=cfg.momentum,
            weight_decay=cfg.weight_decay,
            nesterov=cfg.nesterov,
            compression=cfg.compression,
            density=cfg.density,
            topk_method=cfg.topk_method,
            wire_codec=cfg.wire_codec,
            comm_plan=self._comm_plan_pin or cfg.comm_plan,
            buckets=cfg.buckets,
            pipeline=cfg.pipeline,
            clip_grad_norm=cfg.clip_grad_norm,
            axis_name="dp" if self.p > 1 else None,
            hier_ici_size=cfg.hier_ici,
            warmup_dense_steps=warmup_dense_steps,
            momentum_correction=cfg.momentum_correction,
            _restore_rejected_u=cfg.restore_rejected_u,
            telemetry=cfg.obs_counters,
            telemetry_layers=cfg.obs_layers,
            telemetry_audit_interval=cfg.obs_audit_interval,
        )

    def _set_iters(self, start_epoch: int, skip_steps: int = 0) -> None:
        """(Re)create the persistent per-shard iterators from a given epoch
        permutation — used at init and to fast-forward after restore.
        ``skip_steps`` drains that many optimizer steps' worth of batches
        from each shard on top of the epoch seek: emergency preemption
        checkpoints land MID-epoch, and a bit-exact resumed loss trace
        needs the data stream aligned to the restored step, not the
        enclosing epoch boundary."""

        def gen(ds, start):
            e = start
            while True:
                yield from ds.epoch(e)
                e += 1

        # Stop the old worker BEFORE the new iterators exist: its produce
        # closure must never observe them (a batch it pulled from the new
        # stream would be discarded by close()'s drain — a silent skip).
        self.close()
        iters = [gen(s, start_epoch) for s in self.train_shards]
        for it in iters:
            for _ in range(skip_steps * self.cfg.nsteps_update):
                next(it)
        self._iters = iters
        # (Re)start the background prefetcher on the fresh iterators. The
        # closure binds the local `iters` list, not self._iters, so even a
        # leaked worker could only ever touch its own generation of
        # iterators. The worker assembles numpy batches only;
        # jax.device_put stays on the consumer thread.
        self._prefetch = (
            Prefetcher(lambda: self._stack_shard_batches(iters),
                       depth=self.cfg.prefetch)
            if self.cfg.prefetch > 0 else None
        )

    def close(self) -> None:
        """Release background resources (the prefetch worker and any
        dataset decode pools). Safe to call repeatedly; training can
        continue afterwards only via a new `_set_iters` (restore does
        this — dataset pools re-create lazily) — eval is unaffected."""
        if getattr(self, "_prefetch", None) is not None:
            self._prefetch.close()
            self._prefetch = None
        for ds in (list(getattr(self, "train_shards", []))
                   + [getattr(self, "val_data", None)]):
            if ds is not None and hasattr(ds, "close"):
                ds.close()

    def __enter__(self) -> "Trainer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
        if self.watchdog is not None:
            self.watchdog.close()
        if self.timeline is not None:
            try:
                path = self.timeline.write(self.cfg.obs_timeline)
                self.logger.info("timeline -> %s", path)
            except OSError as e:
                self.logger.warning("timeline write failed: %s", e)
        # End-of-run calibration artifact: the dcn_probe-compatible fit
        # the NEXT run's planner_inputs can consume (copy into the probe
        # dir or pass via --comm-model-fit). Before metrics.close — the
        # registry summary below reads the stream back.
        if (getattr(self, "calib", None) is not None and self.cfg.out_dir
                and self.process_rank == 0):
            try:
                path = self.calib.write_artifact(
                    self.cfg.out_dir, manifest=self._manifest)
                if path:
                    self.logger.info("comm-model fit -> %s", path)
            except OSError as e:
                self.logger.warning("calib artifact write failed: %s", e)
        # End-of-run goodput summary (final=1): BEFORE the registry
        # append below, so the registry line's goodput_frac reads this
        # run's own decomposition back from the stream.
        if self.goodput is not None:
            try:
                self.goodput.log_record(int(self.state.step), final=True)
            except Exception as e:
                self.logger.warning("goodput summary failed: %s", e)
        self._append_registry()
        if getattr(self, "memwatch", None) is not None:
            self.memwatch.close()
        # The metrics file outlives close() (restore() can resume a closed
        # Trainer's training); only leaving the context ends the run.
        self.metrics.close()
        if self.exporter is not None:
            self.exporter.close()

    def _append_registry(self) -> None:
        """One summary line per run into the workspace registry
        (obs/registry.py) — read back offline with `report history` /
        `report regress`. Shared by the normal __exit__ path and the
        watchdog stall path, so an exit-43 run still leaves its line
        (with final_status='stalled') like the 44/45 paths do via
        __exit__. Best-effort: a registry failure never masks the exit
        it is recording."""
        if not (self.cfg.registry and self.cfg.out_dir
                and self.process_rank == 0):
            return
        try:
            from gtopkssgd_tpu.obs import registry as _registry
            from gtopkssgd_tpu.obs.report import load_records
            records, _bad = load_records(self.cfg.out_dir)
            entry = _registry.run_summary(records)
            if entry is not None:
                path = _registry.append_run(self.cfg.registry, entry)
                self.logger.info("registry += %s", path)
        except (OSError, ValueError) as e:
            self.logger.warning("registry append failed: %s", e)

    # ------------------------------------------------------------ watchdog
    def _stall_diagnostics(self) -> Dict[str, Any]:
        """Host-side state merged into the stall record: the span phase
        means of the current logging window (what the run was spending
        time on when it died). Never touches the device — the backend is
        presumed wedged when this runs."""
        return {
            "phase_means_s": {
                path: round(sec, 6)
                for path, sec in self.tracer.stats.summary().items()
            },
        }

    def _on_stall(self, record: Dict[str, Any]) -> None:
        """Persist the diagnostic to metrics.jsonl (line-buffered, so it
        survives the hard exit), then take the default action (stderr dump
        + os._exit(43)). Runs on the watchdog thread while the backend is
        presumed wedged — NOTHING here may touch the device (the stall
        record's own step stands in for state.step), and os._exit skips
        __exit__, so the run's registry line and final records must land
        here or nowhere."""
        step = record.get("step")
        step = int(step) if isinstance(step, (int, float)) else 0
        try:
            self.metrics.log("stall", flush=True, **{
                k: v for k, v in record.items() if k not in ("kind", "time")
            })
            # The exit-43 equivalents of what finalize_resilience and
            # __exit__ write on the 44/45 paths: the final_status the
            # registry line keys on, and the goodput decomposition of
            # the wall this run DID burn before it wedged.
            if self.goodput is not None:
                self.goodput.log_record(step, final=True)
            self.metrics.log(
                "recovery", flush=True, action="summary",
                final_status="stalled", completed=0,
                n_recoveries=(self.recovery.n_recoveries
                              if self.recovery is not None else 0),
                step=step)
            self.metrics.close()
            self._append_registry()
        except Exception:
            pass
        # Best-effort timeline flush: everything here is host-side, and
        # the whole point of the file is correlating exactly this kind of
        # death with what the host was doing.
        if self.timeline is not None:
            try:
                self.timeline.instant("stall", args={
                    k: v for k, v in record.items()
                    if isinstance(v, (int, float, str))})
                self.timeline.write(self.cfg.obs_timeline)
            except Exception:
                pass
        _default_on_stall(record)

    # ------------------------------------------------------------------ lr
    def _lr_schedule(self):
        """Per-dataset step schedules, parity with the reference's hardcoded
        DLTrainer schedules (exact reference epochs unverifiable — mount was
        empty; these are the standard recipes the paper's setup implies).
        ``warmup_epochs`` prepends a linear ramp from base/10 to base
        (large-batch warm-up, the reference C6 settings.py warmup knob)."""
        cfg = self.cfg
        spe = self.steps_per_epoch
        base = cfg.lr
        if cfg.warmup_epochs > 0:
            w = cfg.warmup_epochs * spe
            inner = self._dataset_schedule(base, spe)
            inner_fn = (inner if callable(inner)
                        else (lambda step, v=inner: v))

            def schedule(step):
                ramp = base * (0.1 + 0.9 * jnp.minimum(step, w) / w)
                return jnp.where(step < w, ramp, inner_fn(step))

            return schedule
        return self._dataset_schedule(base, spe)

    def _dataset_schedule(self, base, spe):
        cfg = self.cfg
        if cfg.dataset == "cifar10":
            # x0.1 at 50% and 75% of training (classic CIFAR recipe). For
            # tiny max_epochs the two boundaries can collide or land at
            # step 0 (which would start training at 0.1x lr) — drop such
            # degenerate boundaries instead of silently merging them.
            boundaries = {}
            for frac in (0.5, 0.75):
                b = int(cfg.max_epochs * frac) * spe
                if b > 0 and b not in boundaries:
                    boundaries[b] = 0.1
            return optax.piecewise_constant_schedule(base, boundaries)
        if cfg.dataset == "imagenet":
            return optax.piecewise_constant_schedule(
                base, {30 * spe: 0.1, 60 * spe: 0.1, 80 * spe: 0.1}
            )
        if cfg.dataset == "ptb":
            # constant for 6 epochs then /1.25 per epoch (Zaremba-style decay)
            return lambda step: base * jnp.power(
                0.8, jnp.maximum(0, step // spe - 5)
            )
        if cfg.dataset == "an4":
            # deepspeech-style 1/1.01 per-epoch anneal
            return lambda step: base * jnp.power(1 / 1.01, step // spe)
        return base

    # ---------------------------------------------------------------- state
    def _init_state(self) -> Tuple[TrainState, Any]:
        cfg = self.cfg
        rng = jax.random.PRNGKey(cfg.seed)
        batch = self._peek_batch()
        x = jnp.asarray(batch[self._input_key()][0])
        init_kw = {}
        variables = self.model.init({"params": rng, "dropout": rng}, x, **init_kw)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        opt_state = jax.jit(self.tx.init)(params)
        if self.p > 1:
            # The error-feedback residual is genuinely PER-DEVICE state (it
            # depends on each device's local gradients and top-k picks), so
            # it is carried as an explicit [P, N] leaf sharded P('dp') —
            # unlike the rest of the state, which is replicated.
            # Checkpointing then captures every device's residual, not just
            # device 0's.
            opt_state = expand_residual_per_device(opt_state, self.p, self.mesh)
        n = sum(x.size for x in jax.tree.leaves(params))
        self.num_params = n
        self.logger.info(
            "model=%s dataset=%s params=%.3fM workers=%d compression=%s density=%g",
            cfg.dnn, cfg.dataset, n / 1e6, cfg.nworkers,
            cfg.compression, cfg.density,
        )
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
        )
        if self.spec.name == "lstm":
            one = self.model.initial_carry(cfg.batch_size)
            carry = jax.tree.map(
                lambda a: jnp.broadcast_to(a, (self.p,) + a.shape), one
            )
        else:
            carry = ()
        # Commit every leaf to its steady-state mesh placement. Freshly
        # built jnp arrays are UNCOMMITTED (SingleDeviceSharding), so
        # dispatch 1 would trace against UnspecifiedValue shardings while
        # its outputs come back committed-replicated — and dispatch 2
        # would then retrace and recompile the whole step: a full extra
        # XLA compile at startup and a permanent second cache entry the
        # recompile watch (obs/memwatch.py) flags. The residual is
        # already committed P('dp') by expand_residual_per_device and
        # passes through untouched.
        from jax.sharding import NamedSharding

        rep = NamedSharding(self.mesh, P())

        def commit(leaf):
            if getattr(leaf, "committed", False):
                return leaf
            return jax.device_put(leaf, rep)

        return jax.tree.map(commit, state), jax.tree.map(commit, carry)

    def _input_key(self) -> str:
        return {
            "cifar10": "image", "imagenet": "image",
            "ptb": "tokens", "an4": "spectrogram",
        }[self.cfg.dataset]

    def _peek_batch(self):
        it = iter(self.train_shards[0])
        b = next(it)
        return {k: v[None] for k, v in b.items()}

    def _abstract_batch(self):
        """ShapeDtypeStruct pytree of the canonical global dispatch
        batch ([P, (spd,) nsteps_update, B, ...] — the exact leaves
        _stack_shard_batches assembles), for the AOT compile-accounting
        pass: lowering against it consumes no data and executes
        nothing. Carries the dispatch's real P('dp') sharding so the
        accounted executable is bit-for-bit the one the first dispatch
        runs — which also lets that dispatch hit the persistent
        compilation cache the AOT pass just warmed."""
        from jax.sharding import NamedSharding

        cfg = self.cfg
        lead = ((self.p, cfg.steps_per_dispatch, cfg.nsteps_update)
                if cfg.steps_per_dispatch > 1
                else (self.p, cfg.nsteps_update))
        dp = NamedSharding(self.mesh, P("dp"))
        return {
            k: jax.ShapeDtypeStruct(
                lead + tuple(np.asarray(v[0]).shape),
                np.asarray(v[0]).dtype, sharding=dp)
            for k, v in self._peek_batch().items()
        }

    # ------------------------------------------------------------ loss fns
    def _loss_fn(self, params, batch_stats, carry, batch, rng, train: bool):
        """Per-device loss. Returns (loss, (new_batch_stats, new_carry, aux))."""
        model, name = self.model, self.spec.name
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        mutable = ["batch_stats"] if (train and batch_stats) else []
        kw = dict(train=train, rngs={"dropout": rng} if train else None)

        def run(x, *args):
            if mutable:
                out, mut = model.apply(variables, x, *args, mutable=mutable, **kw)
                return out, mut["batch_stats"]
            return model.apply(variables, x, *args, **kw), batch_stats

        if name == "lstm":
            (logits, new_carry), new_bs = run(batch["tokens"], carry)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["targets"]
            ).mean()
            aux = {"tokens": jnp.asarray(logits.shape[0] * logits.shape[1])}
            return loss, (new_bs, new_carry, aux)
        if name == "lstman4":
            logits, new_bs = run(batch["spectrogram"], batch["input_lengths"])
            t_out = logits.shape[1]
            out_len = self.model.output_length(batch["input_lengths"])
            logit_pad = (
                jnp.arange(t_out)[None, :] >= out_len[:, None]
            ).astype(jnp.float32)
            label_pad = (
                jnp.arange(batch["labels"].shape[1])[None, :]
                >= batch["label_lengths"][:, None]
            ).astype(jnp.float32)
            loss = optax.ctc_loss(
                logits, logit_pad, batch["labels"], label_pad
            ).mean()
            # Eval wants the logits for greedy decode; keep them out of the
            # train path (they'd bloat the scanned aux and be meaningless
            # after averaging).
            aux = {} if train else {"logits": logits}
            return loss, (new_bs, carry, aux)
        # vision
        x = batch["image"]
        if x.dtype == jnp.uint8:
            # Vision pipelines ship raw uint8 pixels across H2D (4x fewer
            # bytes than f32) and normalize HERE, on device, where XLA
            # fuses it into the first conv (wire-format notes in
            # data/cifar.py and data/imagenet.py).
            mean, std = _WIRE_STATS[self.cfg.dataset]
            x = (x.astype(jnp.float32) / 255.0 - jnp.asarray(mean)
                 ) / jnp.asarray(std)
        logits, new_bs = run(x)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        top1 = (logits.argmax(-1) == batch["label"]).mean()
        # top-5 (reference reported top-1/top-5 for vision — SURVEY.md §3.5)
        _, top5_idx = lax.top_k(logits, min(5, logits.shape[-1]))
        top5 = (top5_idx == batch["label"][:, None]).any(-1).mean()
        return loss, (new_bs, carry, {"top1": top1, "top5": top5})

    # ------------------------------------------------------------ the step
    def _build_train_step(self, tx=None):
        cfg, p = self.cfg, self.p
        tx = self.tx if tx is None else tx
        # Recovery holds the pre-step state snapshot across the dispatch
        # (skip restores it bit-identically), so buffer donation is off
        # when a recovery policy is active. safe_donate already returns
        # () on CPU, where every recovery test runs.
        donate = safe_donate(0, 1) if self.recovery is None else ()

        def step(state: TrainState, carry, batch):
            # batch leaves: [nsteps_update, B, ...]; carry: per-device pytree.
            rng = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), state.step)
            if p > 1:
                rng = jax.random.fold_in(rng, lax.axis_index("dp"))

            def micro(acc, xs):
                mb, micro_idx = xs
                grads_sum, bs, cr = acc
                # Each micro-batch draws its own dropout mask (folding the
                # scan index in) — sharing one mask across the accumulation
                # would correlate the micro-gradients.
                mrng = jax.random.fold_in(rng, micro_idx)
                (loss, (bs, cr, aux)), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True
                )(state.params, bs, cr, mb, mrng, True)
                grads_sum = jax.tree.map(jnp.add, grads_sum, grads)
                return (grads_sum, bs, cr), (loss, aux)

            zero_grads = jax.tree.map(jnp.zeros_like, state.params)
            (grads, new_bs, new_carry), (losses, auxes) = lax.scan(
                micro, (zero_grads, state.batch_stats, carry),
                (batch, jnp.arange(cfg.nsteps_update)),
            )
            grads = jax.tree.map(lambda g: g / cfg.nsteps_update, grads)
            updates, opt_state = tx.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            loss = losses.mean()
            aux = jax.tree.map(lambda a: a.mean(), auxes)
            if p > 1:
                loss = lax.pmean(loss, "dp")
                aux = jax.tree.map(lambda a: lax.pmean(a, "dp"), aux)
                if new_bs:
                    new_bs = jax.tree.map(lambda a: lax.pmean(a, "dp"), new_bs)
            new_state = TrainState(
                step=state.step + 1,
                params=params,
                batch_stats=new_bs,
                opt_state=opt_state,
            )
            return new_state, new_carry, loss, aux

        spd = cfg.steps_per_dispatch

        def run_steps(state, c, local_batch):
            """One or spd optimizer steps on the stripped (per-device)
            state. With spd > 1 the batch leaves carry an extra leading
            [spd] axis and the step runs under lax.scan — one dispatch,
            spd updates; per-step RNG stays exact because step() derives
            it from state.step, which increments inside the scan."""
            if spd == 1:
                return step(state, c, local_batch)

            def body(sc, mb):
                s, cc = sc
                s, cc, loss, aux = step(s, cc, mb)
                return (s, cc), (loss, aux)

            (s, c2), (losses, auxes) = lax.scan(
                body, (state, c), local_batch)
            # Report the LAST scanned step's loss/aux — identical
            # convention to the per-step path, whose caller also reads
            # the most recent step.
            return (s, c2, losses[-1],
                    jax.tree.map(lambda a: a[-1], auxes))

        def shardwise(state, carry, batch):
            # Both the p==1 direct path and the per-device shard_map block
            # see a leading shard dim of size 1 — strip it, run, restore.
            # The residual travels the same way: global [P, N], per-device
            # [1, N] inside the block, [N] inside step().
            c = jax.tree.map(lambda a: a[0], carry) if carry != () else ()
            if p > 1:
                # tree.map covers both the flat-[N] residual and the
                # layerwise per-leaf tuple.
                state = state._replace(opt_state=state.opt_state._replace(
                    residual=jax.tree.map(
                        lambda r: r[0], state.opt_state.residual)))
            s, c2, loss, aux = run_steps(
                state, c, jax.tree.map(lambda b: b[0], batch)
            )
            if p > 1:
                s = s._replace(opt_state=s.opt_state._replace(
                    residual=jax.tree.map(
                        lambda r: r[None], s.opt_state.residual)))
            if carry != ():
                c2 = jax.tree.map(lambda a: a[None], c2)
            return s, c2, loss, aux

        if p == 1:
            return jax.jit(shardwise, donate_argnums=donate)

        # Per-leaf specs: everything in the state is replicated EXCEPT the
        # error-feedback residual, which is per-device ([P, N], sharded over
        # 'dp'). check_vma stays off for a structural reason: the gtopk
        # result is value-identical on every device (the hypercube merge is
        # symmetric) but built from lax.ppermute exchanges, which the
        # varying-axes checker must conservatively type as device-varying —
        # it cannot prove value-level replication without an O(N) collective
        # on the hot path. Replication of params/opt state is instead
        # asserted by tests (tests/test_optimizer.py replica-consistency,
        # tests/test_trainer.py::test_residual_sharding_multiworker).
        state_spec = TrainState(
            step=P(), params=P(), batch_stats=P(),
            # telemetry scalars are pmean'd inside the optimizer, so P()
            # (replicated) is sound for them.
            opt_state=GTopKSGDState(count=P(), residual=P("dp"), inner=P(),
                                    telemetry=P()),
        )
        smapped = jax.shard_map(
            shardwise,
            mesh=self.mesh,
            in_specs=(state_spec, P("dp"), P("dp")),
            out_specs=(state_spec, P("dp"), P(), P()),
            check_vma=False,
        )
        return jax.jit(smapped, donate_argnums=donate)

    def _build_eval_step(self):
        """Eval step; sharded over the mesh when p > 1 (VERDICT round-2
        weak #6: the reference evaluated rank-0-only — SURVEY.md §3.5 —
        which serializes the whole val set through one chip while P-1
        idle; TPU-first eval spreads P val batches per dispatch over the
        same P('dp') convention training uses, so eval walltime scales
        ~1/P). The PTB LSTM keeps the sequential path: its eval threads a
        BPTT carry through the val stream in order, which is semantically
        serial. Per-shard metrics come back un-reduced ([P]-leading) and
        are weighted on host — identical arithmetic to the sequential
        path, no psum needed."""
        def ev(params, batch_stats, carry, batch):
            loss, (_, new_carry, aux) = self._loss_fn(
                params, batch_stats, carry, batch,
                jax.random.PRNGKey(0), False,
            )
            return loss, new_carry, aux

        # Multi-process runs keep the sequential path too: the sharded
        # step's [P]-leading outputs span non-addressable devices there,
        # so np.asarray on them would raise — and with 1 device per host
        # there is nothing to shard locally anyway.
        if (self.p == 1 or self.spec.name == "lstm"
                or jax.process_count() > 1):
            def single(state, carry, batch):
                return ev(state.params, state.batch_stats, carry, batch)
            self._eval_sharded = False
            return jax.jit(single)

        def block(params, batch_stats, batch):
            # [1, B, ...] per-device shard -> strip, run, restore the
            # leading dim so out_specs P('dp') reassembles [P] metrics.
            loss, _, aux = ev(params, batch_stats, (),
                              jax.tree.map(lambda b: b[0], batch))
            pad = lambda a: a[None]
            return pad(loss), jax.tree.map(pad, aux)

        smapped = jax.shard_map(
            block, mesh=self.mesh,
            in_specs=(P(), P(), P("dp")),
            out_specs=(P("dp"), P("dp")),
            check_vma=False,
        )

        def sharded(state, carry, batch):
            loss, aux = smapped(state.params, state.batch_stats, batch)
            return loss, carry, aux

        self._eval_sharded = True
        return jax.jit(sharded)

    # ------------------------------------------------------------- batches
    def _stack_shard_batches(self, iters) -> Dict[str, np.ndarray]:
        """[P_local, nsteps_update, B, ...] host-side batch — the leading
        dim is the shard_map 'dp' dim; this process contributes its local
        mesh positions only."""
        n = self.cfg.nsteps_update
        per_shard = []
        for it in iters:
            micro = [next(it) for _ in range(n)]
            per_shard.append(
                {k: np.stack([m[k] for m in micro]) for k in micro[0]}
            )
        return {
            k: np.stack([s[k] for s in per_shard]) for k in per_shard[0]
        }

    def _device_batch(self, np_batch: Dict[str, np.ndarray]):
        """Host batch -> device arrays sharded P('dp') over the mesh. In a
        multi-host run the local [P_local, ...] block is this process's
        contribution to the global [P, ...] array."""
        if jax.process_count() == 1:
            return {k: jnp.asarray(v) for k, v in np_batch.items()}
        from jax.sharding import NamedSharding

        sharding = NamedSharding(self.mesh, P("dp"))
        return {
            k: jax.make_array_from_process_local_data(sharding, v)
            for k, v in np_batch.items()
        }

    def _fetch_host(self, step: int, spd: int) -> Dict[str, np.ndarray]:
        """One host batch from the prefetcher (or synchronously). With an
        injector active, loader faults (injected or real) are absorbed by
        the shared retry helper — a transient IOError costs a retry, not
        the run."""
        def fetch():
            if self.injector is not None:
                self.injector.check_loader(step, step + spd)
            return (next(self._prefetch) if self._prefetch is not None
                    else self._stack_shard_batches(self._iters))

        if self.injector is None:
            return fetch()
        from gtopkssgd_tpu.resilience import retry_call

        return retry_call(fetch, retries=2, delay=0.05,
                          logger=self.logger, desc="host batch fetch")

    # -------------------------------------------------------------- train
    def train(self, num_iters: int, epoch: int = 0) -> Dict[str, float]:
        """Run `num_iters` optimizer steps (reference DLTrainer.train)."""
        cfg = self.cfg
        inj, rec, guard = self.injector, self.recovery, self.preempt
        gp = self.goodput
        t_start, samples = time.perf_counter(), 0
        last_loss, last_aux = float("nan"), {}
        if num_iters <= 0:
            return {"loss": float("nan"), "throughput": 0.0, "wall": 0.0}
        # Host-side mirror of state.step: reading int(self.state.step) would
        # block on the device every iteration and kill async IO/compute
        # overlap; the mirror is exact (the step increments by
        # steps_per_dispatch per dispatch, and so does the mirror below).
        step = int(self.state.step)
        if self.cfg.prefetch > 0 and self._prefetch is None:
            # close() drained batches the worker had already pulled from
            # self._iters; silently falling back to the sync path would
            # skip them. Training may only resume through _set_iters
            # (restore() does this) or a fresh Trainer.
            raise RuntimeError(
                "Trainer is closed; build a new Trainer (restore() "
                "re-opens it only when a saved checkpoint exists)"
            )
        spd = cfg.steps_per_dispatch
        if spd > 1 and num_iters % spd != 0:
            raise ValueError(
                f"num_iters={num_iters} must be a multiple of "
                f"steps_per_dispatch={spd} (one compiled program per "
                "dispatch shape; a ragged tail would compile a second)")
        wd = self.watchdog
        if wd is not None:
            wd.arm("train", step=step)
        if gp is not None:
            # First entry: everything since init not yet attributed is
            # startup; re-entries (fit()'s later epochs) drop the
            # inter-epoch span (eval/ckpt marked their own shares; the
            # rest is honestly `other`).
            gp.train_started()
        try:
            for _ in range(num_iters // spd if spd > 1 else num_iters):
                # Preemption flag check at the iteration boundary: the
                # signal handler (resilience/preempt.py) only sets the
                # flag; the emergency save + unwind happen HERE, where
                # the state is whole. Under --elastic a preemption is a
                # RESIZE to P-1 (the fleet re-forms without the lost
                # capacity) unless that would shrink below min_fleet,
                # in which case _resize_now falls back to exit-45
                # preempt semantics.
                if guard is not None and guard.triggered:
                    if cfg.elastic:
                        self._resize_now(self.p - 1, reason="preempt")
                    self._preempt_now()
                # Degrade cooldown expiry: re-enter the sparse step.
                if self._degraded and step >= self._degrade_until:
                    self._train_step = self._sparse_step
                    self._degraded = False
                    if rec is not None:
                        rec.degraded = False
                        rec.record("sparse_resume", step=step)
                if inj is not None:
                    inj.sleep_if_slow(step, step + spd)
                    if gp is not None:
                        # Injected slowness is exactly the skew-wait the
                        # taxonomy's `wait` bucket accounts.
                        gp.mark("wait")
                with self.tracer.span("io"):
                    hosts = [self._fetch_host(step, spd)
                             for _ in range(spd)]
                    if spd == 1:
                        host = hosts[0]
                    else:
                        # [P, spd, nsteps_update, B, ...]: the scan axis
                        # sits after the shard dim (shardwise strips dim 0
                        # first).
                        host = {
                            k: np.stack([h[k] for h in hosts], axis=1)
                            for k in hosts[0]
                        }
                    if inj is not None:
                        # reshape fault: a deliberately different
                        # dispatch shape (B axis sits after the shard —
                        # and with spd > 1 the scan — dim).
                        host = inj.reshape_batch(
                            host, step, step + spd,
                            axis=2 if spd == 1 else 3)
                    batch = self._device_batch(host)
                if gp is not None:
                    gp.mark("data")  # host batch assembly + H2D
                if rec is not None:
                    # Pre-step snapshot: what a `skip` action restores.
                    # Valid across the dispatch because donation is
                    # disabled whenever recovery is active.
                    prev_state, prev_carry = self.state, self.carry
                if inj is not None:
                    self.state = inj.poison_params(
                        self.state, step, step + spd)
                calib_now = (
                    self.calib is not None and cfg.obs_calib_interval > 0
                    and (step + spd) % cfg.obs_calib_interval < spd)
                # Critpath rides the SAME capture cadence (and the same
                # captured trace, when both are on) — one profiled
                # dispatch serves both consumers.
                critpath_now = (
                    cfg.obs_critpath and cfg.obs_calib_interval > 0
                    and (step + spd) % cfg.obs_calib_interval < spd)
                capture_now = calib_now or critpath_now
                with self.tracer.span("dispatch"):
                    # Async enqueue only — the span must NOT drain the
                    # queue (the overlap is the point); device time shows
                    # under the same name in a profiler trace.
                    if capture_now:
                        # Calibration sample: profile exactly this
                        # dispatch, blocking inside the capture so the
                        # device comm events land in the trace — a sync
                        # plus profiler overhead, which is why the
                        # cadence is opt-in (obs_calib_interval).
                        import tempfile

                        from gtopkssgd_tpu.obs.trace_attr import capture
                        trace_dir = tempfile.mkdtemp(prefix="calib_trace_")
                        with capture(trace_dir):
                            self.state, self.carry, loss, aux = (
                                self._train_step(self.state, self.carry,
                                                 batch))
                            jax.block_until_ready(loss)
                    else:
                        self.state, self.carry, loss, aux = self._train_step(
                            self.state, self.carry, batch
                        )
                samples += (cfg.batch_size * cfg.nworkers
                            * cfg.nsteps_update * spd)
                if gp is not None:
                    # The dispatch span is step time: split by the
                    # latest critpath stage fractions (all goodput until
                    # one exists); while degraded, the excess over the
                    # clean-step EWMA is the degraded-mode delta.
                    gp.step_mark(begin=True, degraded=self._degraded)
                step += spd
                if critpath_now:
                    # Must run BEFORE the calibrator feed — that call
                    # deletes the trace dir when it finishes.
                    self._log_critpath(step, spd, trace_dir,
                                       cleanup=not calib_now)
                if calib_now:
                    self._feed_calibrator(step, spd, trace_dir)
                if capture_now and self.forecaster is not None:
                    # One forecast per capture: compose the budgets and
                    # fit the two feeds above just refreshed into a
                    # durable "forecast" record, then the drift rule
                    # (which may raise AnomalyHalt — after the record).
                    self.forecaster.observe(step)
                if capture_now and gp is not None:
                    # Host-side trace attribution is observability
                    # overhead — no taxonomy bucket; drop it to `other`
                    # rather than inflate a category it isn't.
                    gp.mark(None)
                if inj is not None:
                    # preempt injection delivers a real SIGTERM through
                    # the installed guard; the flag check right after
                    # makes the firing step-deterministic.
                    inj.maybe_preempt(step - spd, step, guard)
                    # resize@K:NEWP / evict_rank:R@K fire at the same
                    # post-dispatch boundary (durable "inject" record
                    # either way; no-op warning without --elastic).
                    self._check_injected_resize(step - spd, step)
                if guard is not None and guard.triggered:
                    if cfg.elastic:
                        self._resize_now(self.p - 1, reason="preempt")
                    self._preempt_now()
                synced = False
                # On-device counters (obs.counters, carried in
                # opt_state.telemetry). float() blocks until the
                # dispatched step actually ran — which is also the
                # watchdog's honest progress proof.
                observed = False
                if (cfg.obs_counters and cfg.obs_interval > 0
                        and step % cfg.obs_interval < spd):
                    tel = self.state.opt_state.telemetry
                    if tel:
                        with self.tracer.span("obs_read"):
                            # Scalar counters -> one "obs" record; the
                            # per-layer [L] arrays -> one "layers" record
                            # per layer; the [N] age buffer stays on
                            # device (its per-layer mean is already in
                            # the layers record).
                            scalars = telemetry_scalars(tel)
                            self.metrics.log("obs", step=step, **scalars)
                            max_age = None
                            lay = tel.get("layers")
                            if lay is not None:
                                cols = {f: np.asarray(v)
                                        for f, v in lay.items()}
                                ages = cols.get("residual_age")
                                if ages is not None and ages.size:
                                    max_age = float(np.max(ages))
                                for i, lname in enumerate(
                                        self._layer_names):
                                    self.metrics.log(
                                        "layers", step=step, layer=lname,
                                        **{f: float(c[i])
                                           for f, c in cols.items()})
                            if self.timeline is not None:
                                self.timeline.counter("obs", scalars)
                        # The step is already synced by the reads above,
                        # so feeding the monitor costs nothing extra.
                        if self.monitor is not None:
                            self.monitor.observe(
                                step, loss=float(loss), telemetry=scalars,
                                max_residual_age=max_age)
                            observed = True
                        synced = True
                # With spd > 1 a dispatch may jump over the exact
                # boundary; log when any step inside it crossed one.
                if step % cfg.log_interval < spd:
                    last_loss = float(loss)
                    last_aux = {k: float(v) for k, v in aux.items()}
                    elapsed = time.perf_counter() - t_start
                    row = dict(
                        step=step, epoch=epoch, loss=last_loss,
                        throughput=samples / elapsed, **last_aux,
                    )
                    if cfg.dataset == "ptb":
                        row["ppl"] = float(np.exp(min(last_loss, 20.0)))
                    self.metrics.log("train", **row)
                    self.tracer.flush(step)
                    if self.timeline is not None:
                        self.timeline.counter("train", row)
                    # Monitor at the log cadence too, so NaN detection
                    # works with obs counters disabled (loss only — the
                    # float() above already paid the sync).
                    if self.monitor is not None and not observed:
                        self.monitor.observe(step, loss=last_loss)
                        observed = True
                    synced = True
                if gp is not None:
                    # The obs/log blocking reads drained the dispatched
                    # step — that wait IS step time, same split as the
                    # dispatch span (tiny when nothing synced).
                    gp.step_mark(degraded=self._degraded)
                if rec is not None:
                    # Apply any actions the monitor's claims queued this
                    # iteration. `step` may rewind (skip/rollback restore
                    # an earlier state) — the host mirror follows the
                    # restored state.step so the data stream and LR
                    # schedule stay aligned.
                    pending = rec.pop_pending()
                    if pending:
                        step = self._apply_recovery(
                            pending, prev_state, prev_carry, step)
                    elif observed:
                        rec.note_ok()
                if wd is not None and synced:
                    wd.heartbeat(step=step)
                if self.memwatch is not None and synced:
                    # Compile/memory watch at a sync the loop already
                    # paid: accounts a never-seen dispatch shape (one
                    # fsync'd "compile" record), logs executable-cache
                    # growth, samples live memory every
                    # obs_mem_interval steps. May raise AnomalyHalt
                    # (recompile_storm / device_mem_leak /
                    # hbm_headroom) — records are durably written
                    # first.
                    self.memwatch.poll(
                        step, fn=self._train_step,
                        args=(self.state, self.carry, batch))
                    if gp is not None:
                        # A never-seen dispatch shape AOT-compiles here;
                        # warm polls cost ~nothing.
                        gp.mark("compile")
                if gp is not None and synced:
                    # Periodic durable "goodput" record + the
                    # goodput_collapse feed, at a sync the loop already
                    # paid. AnomalyHalt propagates AFTER the record is
                    # durable, like every monitor halt.
                    gp.tick(step)
                    # Elastic eviction self-check, every
                    # evict_after_windows goodput windows (rank 0 — it
                    # owns the merged fleet view): a persistently
                    # underperforming rank named by goodput advise()
                    # triggers the evict resize path.
                    if (cfg.elastic and cfg.evict_after_windows > 0
                            and cfg.obs_goodput_interval > 0
                            and cfg.out_dir
                            and self.process_rank == 0
                            and step % (cfg.obs_goodput_interval
                                        * cfg.evict_after_windows)
                            < spd):
                        self._maybe_evict(step)
            # true_sync, not block_until_ready: the tunneled TPU platform
            # acks readiness before execution completes (utils/timers.py).
            from gtopkssgd_tpu.utils import true_sync

            with self.tracer.span("final_sync"):
                true_sync(self.state.params)
            if gp is not None:
                # Draining the last dispatched steps is step time too.
                gp.step_mark(degraded=self._degraded)
            if wd is not None:
                wd.heartbeat(step=step)
        finally:
            if wd is not None:
                wd.disarm()
        wall = time.perf_counter() - t_start
        return {
            "loss": float(loss),
            "throughput": samples / wall,
            "wall": wall,
            **{k: float(v) for k, v in aux.items()},
        }

    # --------------------------------------------------------------- eval
    def test(self) -> Dict[str, float]:
        """Full-validation metrics (reference DLTrainer.test): top-1 for
        vision, perplexity for PTB, greedy-decode CER for AN4. When the
        eval step is sharded (p > 1, non-LSTM) the val stream is consumed
        in groups of P batches per dispatch; a partial tail group is
        padded by repeating its last batch, with the pad shards excluded
        from the host-side weighting (weight bookkeeping is per REAL
        batch, so the numbers are identical to the sequential path)."""
        cfg = self.cfg
        name = self.spec.name
        losses, top1s, top5s, weights = [], [], [], []
        cer_counts = np.zeros(4, np.int64)  # char errs, chars, word errs, words
        carry = (
            self.model.initial_carry(cfg.batch_size) if name == "lstm" else ()
        )

        def account(batch, loss, aux):
            losses.append(float(loss))
            weights.append(len(next(iter(batch.values()))))
            if "top1" in aux:
                top1s.append(float(aux["top1"]))
            if "top5" in aux:
                top5s.append(float(aux["top5"]))
            if name == "lstman4":
                cer_counts[:] += self._greedy_error_counts(
                    batch, aux["logits"])

        def flush_group(group):
            nvalid = len(group)
            while len(group) < self.p:  # pad shards, zero-weighted below
                group.append(group[-1])
            stacked = {
                k: np.stack([np.asarray(b[k]) for b in group])
                for k in group[0]
            }
            loss, _, aux = self._eval_step(
                self.state, (), self._device_batch(stacked))
            loss = np.asarray(loss)
            aux = {k: np.asarray(v) for k, v in aux.items()}
            for i in range(nvalid):
                account(group[i], loss[i],
                        {k: v[i] for k, v in aux.items()})

        group = []
        for i, batch in enumerate(self.val_data.epoch(0)):
            if cfg.eval_batches is not None and i >= cfg.eval_batches:
                break
            if getattr(self, "_eval_sharded", False):
                group.append(batch)
                if len(group) == self.p:
                    flush_group(group)
                    group = []
                continue
            jb = {k: jnp.asarray(v) for k, v in batch.items()}
            loss, carry_out, aux = self._eval_step(self.state, carry, jb)
            if name == "lstm":
                carry = carry_out
            account(jb, loss, aux)
        if group:
            flush_group(group)
        w = np.asarray(weights, np.float64)
        mean_loss = float(np.average(losses, weights=w)) if losses else float("nan")
        out = {"val_loss": mean_loss}
        if top1s:
            out["val_top1"] = float(np.average(top1s, weights=w))
        if top5s:
            out["val_top5"] = float(np.average(top5s, weights=w))
        if cfg.dataset == "ptb":
            out["val_ppl"] = float(np.exp(min(mean_loss, 20.0)))
        if cer_counts[1] > 0:
            out["val_cer"] = float(cer_counts[0] / cer_counts[1])
            out["val_wer"] = float(cer_counts[2] / max(1, cer_counts[3]))
        self.metrics.log("eval", step=int(self.state.step), **out)
        if self.goodput is not None:
            # Eval is productive work — the job exists to train AND
            # measure the model — so it accrues to goodput, not to a
            # badput bucket (the taxonomy has none for it) and not to
            # `other` (which must stay an accounting gap, pinned ~0 on
            # clean runs by the gate smoke).
            self.goodput.mark("goodput")
        return out

    # Space in the 29-char AN4 vocabulary (LABELS = "_'A..Z ") — word
    # boundary for WER.
    _AN4_SPACE_ID = 28

    def _greedy_error_counts(self, batch, logits) -> np.ndarray:
        """Greedy CTC decode -> [char_errors, chars, word_errors, words]
        (reference reported WER/CER for AN4 via greedy decode — SURVEY.md
        §3.5). Error rates are aggregated corpus-level (sum of edit
        distances / sum of reference lengths), the standard ASR convention.
        `logits` come from the jitted eval step — no second forward pass;
        the blank/repeat collapse is vectorized, only the per-utterance
        edit distance (C++, gtopkssgd_tpu.native) runs in a loop."""
        pred = np.asarray(logits.argmax(-1))  # [B, T']
        out_len = np.asarray(self.model.output_length(batch["input_lengths"]))
        labels = np.asarray(batch["labels"])
        lab_len = np.asarray(batch["label_lengths"])
        bsz, t_out = pred.shape
        valid = np.arange(t_out)[None, :] < out_len[:, None]
        prev = np.concatenate(
            [np.zeros((bsz, 1), pred.dtype), pred[:, :-1]], axis=1)
        keep = valid & (pred != 0) & (pred != prev)

        def words(seq):
            out, cur = [], []
            for c in seq:
                if c == self._AN4_SPACE_ID:
                    if cur:
                        out.append(tuple(cur))
                    cur = []
                else:
                    cur.append(c)
            if cur:
                out.append(tuple(cur))
            return out

        counts = np.zeros(4, np.int64)
        for b in range(bsz):
            seq = pred[b][keep[b]].tolist()
            ref = labels[b, : lab_len[b]].tolist()
            counts[0] += native.edit_distance(seq, ref)
            counts[1] += max(1, len(ref))
            # word-level: map word tuples to ids, edit-distance those
            sw, rw = words(seq), words(ref)
            ids = {}
            to_ids = lambda ws: [ids.setdefault(t, len(ids)) for t in ws]
            counts[2] += native.edit_distance(to_ids(sw), to_ids(rw))
            counts[3] += max(1, len(rw))
        return counts

    # ----------------------------------------------------------- epochs/ckpt
    def fit(self, max_epochs: Optional[int] = None) -> Dict[str, float]:
        """Epoch loop: train + eval + checkpoint (reference dist_trainer
        main loop)."""
        cfg = self.cfg
        epochs = max_epochs or cfg.max_epochs
        if cfg.steps_per_dispatch > 1 and (
                self.steps_per_epoch % cfg.steps_per_dispatch != 0):
            raise ValueError(
                f"steps_per_dispatch={cfg.steps_per_dispatch} must divide "
                f"steps_per_epoch={self.steps_per_epoch} for epoch "
                "training (train() dispatches fixed-shape programs)")
        result = {}
        # Resume-aware: a restored state at step S has completed S /
        # steps_per_epoch epochs; train only the remainder (restore() already
        # fast-forwarded the data iterators to this epoch's permutation).
        start_epoch = int(self.state.step) // self.steps_per_epoch
        for epoch in range(start_epoch, epochs):
            self.reset_carry()  # BPTT state does not cross epochs (ref §3.4)
            train_stats = self.train(self.steps_per_epoch, epoch=epoch)
            result = {**train_stats, **self.test()}
            self.metrics.log("epoch", epoch=epoch, **result)
            if self._ckpt is not None:
                self.save()
        return result

    def reset_carry(self) -> None:
        """Zero the recurrent carry (epoch boundary: each PTB row restarts at
        its stream start, so end-of-corpus state must not leak in)."""
        if self.spec.name == "lstm":
            one = self.model.initial_carry(self.cfg.batch_size)
            self.carry = jax.tree.map(
                lambda a: jnp.broadcast_to(a, (self.p,) + a.shape), one
            )

    def save(self) -> None:
        """Orbax save of the LIVE (sharded) state. Every process must call
        this — orbax coordinates multi-host writes internally and each
        process persists its addressable shards of the P('dp') residual;
        a host-side numpy conversion would crash on multi-host (the
        residual spans non-addressable devices) and was how round 1 lost
        every rank-but-0 residual."""
        if self._ckpt is not None:
            # meta.residual_p: the residual's partition width, so an
            # elastic different-P resume can build the OLD-shape
            # template without guessing (utils/checkpoint.py sidecar).
            self._ckpt.save(int(self.state.step), self.state,
                            meta={"residual_p": self.p})
            if self.goodput is not None:
                self.goodput.mark("ckpt")

    def restore(self) -> bool:
        if self._ckpt is None or self._ckpt.latest_step() is None:
            return False
        if self.injector is not None:
            # corrupt_ckpt@latest fires here, right before the read — the
            # restore path's torn-checkpoint fallback is what's under test.
            self.injector.maybe_corrupt_ckpt(self._ckpt.directory)
        # Abstract template with explicit shardings: orbax restores every
        # leaf directly INTO its target placement — replicated over the
        # mesh for params/step/momentum, P('dp') for the per-device
        # residual (no dense single-device materialization, and every
        # process of a multi-host run reads only its own residual shards).
        # Elastic resumes first consult the sidecar's residual_p: a
        # checkpoint saved at a DIFFERENT fleet size takes the
        # re-partitioning path instead of the shape-identical one.
        old_p = 0
        if self.cfg.elastic:
            old_p = int(self._ckpt.sidecar_meta().get("residual_p") or 0)
        if (old_p and old_p != self.p
                and getattr(self.state.opt_state, "residual", None)
                is not None):
            self.state = self._restore_resized(old_p)
        else:
            self.state = self._ckpt.restore(
                self._state_template(),
                allow_mismatch=self.cfg.allow_ckpt_mismatch)
        step = int(self.state.step)
        self.logger.info("restored step %d from %s", step,
                         self._ckpt.directory)
        # Fast-forward the data stream to the restored position. Epoch
        # checkpoints land on a boundary (skip_steps=0); emergency
        # preemption saves land MID-epoch, and the remainder drains that
        # many steps' batches so the resumed trace is the uninterrupted
        # one.
        self._set_iters(step // self.steps_per_epoch,
                        skip_steps=step % self.steps_per_epoch)
        if self.goodput is not None:
            # Restore + iterator fast-forward are checkpoint cost.
            self.goodput.mark("ckpt")
        return True

    def _restore_resized(self, old_p: int):
        """Elastic restore across a fleet resize: the checkpoint's
        residual is partitioned over ``old_p`` rows, this run's over
        ``self.p``. Build a template in the SAVED shape — replicated,
        since old_p need not divide the new mesh — so the integrity
        digest verifies against what was actually written, then
        re-partition the residual host-side (resilience/elastic.py:
        grow = zero rows, shrink = masked-fold addition conserving the
        pending gradient mass) and commit it onto the new mesh's
        P('dp') placement. Every other leaf restores shape-identically."""
        from jax.sharding import NamedSharding

        from gtopkssgd_tpu.resilience.elastic import repartition_buffer

        rep = NamedSharding(self.mesh, P())

        def leaf(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=rep)

        template = jax.tree.map(leaf, self.state)

        def old_leaf(r):
            # live residual: [p, ...] rows when p > 1, bare at p == 1;
            # the saved one followed the same convention at old_p
            body = r.shape[1:] if self.p > 1 else r.shape
            shape = ((old_p,) + tuple(body)) if old_p > 1 else tuple(body)
            return jax.ShapeDtypeStruct(shape, r.dtype, sharding=rep)

        template = template._replace(opt_state=template.opt_state._replace(
            residual=jax.tree.map(old_leaf,
                                  self.state.opt_state.residual)))
        restored = self._ckpt.restore(
            template, allow_mismatch=self.cfg.allow_ckpt_mismatch)
        dp = NamedSharding(self.mesh, P("dp"))

        def repartition(saved):
            buf = np.asarray(saved)
            if old_p == 1:
                buf = buf[None]
            out = repartition_buffer(buf, max(1, self.p))
            if self.p == 1:
                return jnp.asarray(out[0])
            return jax.make_array_from_callback(
                out.shape, dp, lambda idx, o=out: o[idx])

        restored = restored._replace(opt_state=restored.opt_state._replace(
            residual=jax.tree.map(repartition,
                                  restored.opt_state.residual)))
        self.logger.warning(
            "elastic restore: residual re-partitioned %d -> %d rows "
            "(pending gradient mass conserved)", old_p, self.p)
        return restored

    # ---------------------------------------------------------- resilience
    def _preempt_now(self) -> None:
        """The preemption flag is set: force a step-granular emergency
        save (orbax force=True — the step may equal an existing epoch
        save) and unwind via Preempted, which dist_trainer maps to exit
        45. Runs on the train-loop thread where the state is whole."""
        from gtopkssgd_tpu.resilience import Preempted

        step = int(self.state.step)  # blocks: the save must be post-step
        if self._ckpt is not None:
            self._ckpt.save(step, self.state, force=True,
                            meta={"residual_p": self.p})
            if self.goodput is not None:
                # The emergency save is the preempt fault's designated
                # badput: ckpt.
                self.goodput.mark("ckpt")
            self.metrics.log("recovery", flush=True,
                             action="emergency_save", step=step)
            self.logger.warning(
                "preemption: emergency checkpoint at step %d -> %s",
                step, self._ckpt.directory)
        else:
            self.logger.warning(
                "preemption at step %d with no out_dir: nothing saved",
                step)
        raise Preempted(f"preemption signal at step {step}")

    def _resize_now(self, new_p: int, *, reason: str,
                    evicted_ranks=()) -> None:
        """Elastic resize: drain (the caller sits at an iteration
        boundary; int(state.step) blocks until the state is whole) ->
        emergency-save with the residual's partition width in the
        sidecar meta -> rewrite the elastic.json lineage file for the
        new P -> durable "resize" record -> ResizeRestart, which
        dist_trainer maps to exit 46. Everything lands on disk BEFORE
        the unwind, so the supervisor can relaunch the moment the
        process exits. A resize below min_fleet is refused: preemption
        falls back to classic exit-45 semantics, an eviction downgrades
        to a warning."""
        from gtopkssgd_tpu.resilience.elastic import (
            ResizeRestart, mint_lineage_id, write_lineage)

        cfg = self.cfg
        new_p = int(new_p)
        floor = max(1, cfg.min_fleet)
        if new_p < floor:
            self.logger.warning(
                "elastic: refusing resize %d -> %d below min_fleet=%d "
                "(%s)", self.p, new_p, floor, reason)
            if reason == "preempt":
                self._preempt_now()
            return
        if self._ckpt is None:
            self.logger.warning(
                "elastic: resize (%s) at step %d with no out_dir — "
                "nothing to hand the relaunch; ignoring",
                reason, int(self.state.step))
            return
        step = int(self.state.step)  # blocks: the save must be post-step
        self._ckpt.save(step, self.state, force=True,
                        meta={"residual_p": self.p})
        if self.goodput is not None:
            self.goodput.mark("ckpt")
        lineage = dict(self.lineage or {})
        lineage.update(
            lineage_id=lineage.get("lineage_id") or mint_lineage_id(),
            resize_epoch=int(lineage.get("resize_epoch", 0)) + 1,
            prev_p=self.p, p=new_p, reason=reason,
            evicted_ranks=[int(r) for r in evicted_ranks],
            drained_step=step)
        write_lineage(cfg.out_dir, **lineage)
        self.lineage = lineage
        self.metrics.log(
            "resize", flush=True, step=step, old_p=self.p, new_p=new_p,
            reason=reason,
            evicted_ranks=[int(r) for r in evicted_ranks],
            drained_step=step, restore_step=step,
            lineage_id=lineage["lineage_id"],
            resize_epoch=lineage["resize_epoch"])
        self.logger.warning(
            "elastic resize (%s): p %d -> %d at step %d; checkpoint + "
            "lineage durable under %s — relaunch with --resume "
            "--elastic --nworkers %d", reason, self.p, new_p, step,
            cfg.out_dir, new_p)
        raise ResizeRestart(
            f"resize {self.p} -> {new_p} ({reason}) at step {step}")

    def _check_injected_resize(self, prev: int, new: int) -> None:
        """Injected resize@K:NEWP / evict_rank:R@K at the step
        boundary. The durable "inject" record lands either way; without
        cfg.elastic the request downgrades to a warning, so a chaos
        spec cannot opt a run into semantics its flags didn't."""
        inj, cfg = self.injector, self.cfg
        new_p = inj.pending_resize(prev, new)
        if new_p is not None:
            if not cfg.elastic:
                self.logger.warning(
                    "inject: resize to P=%d ignored — run without "
                    "--elastic", new_p)
            else:
                self._resize_now(new_p, reason="inject")
        rank = inj.pending_evict(prev, new)
        if rank is not None:
            if not cfg.elastic:
                self.logger.warning(
                    "inject: evict_rank %d ignored — run without "
                    "--elastic", rank)
            else:
                self._resize_now(self.p - 1, reason="evict",
                                 evicted_ranks=(rank,))

    def _maybe_evict(self, step: int) -> None:
        """Elastic eviction self-check (every evict_after_windows
        goodput windows): merge this run's per-rank metric shards and
        act on resilience/elastic.py's ``eviction_decision`` — goodput
        ``advise()`` names the rank, the straggler EWMA corroborates.
        Naturally inert for single-shard runs (advise needs >= 2
        ranks' ledgers) and when the merge cannot be built: the
        self-check must never take down a healthy run."""
        cfg = self.cfg
        try:
            from gtopkssgd_tpu.obs import fleet
            from gtopkssgd_tpu.resilience.elastic import eviction_decision
            merged = fleet.merge([cfg.out_dir])
            decision = eviction_decision(
                merged, p=self.p, min_fleet=cfg.min_fleet)
        except Exception as e:
            self.logger.debug(
                "elastic: eviction self-check skipped (%s: %s)",
                type(e).__name__, e)
            return
        if decision is None:
            return
        self.logger.warning("elastic: eviction decision %s", decision)
        self._resize_now(decision["new_p"], reason="evict",
                         evicted_ranks=(decision["rank"],))

    def _apply_recovery(self, pending, prev_state, prev_carry,
                        step: int) -> int:
        """Apply the actions claimed during this iteration's monitor
        observations. Returns the (possibly rewound) host step mirror."""
        from gtopkssgd_tpu.obs.events import AnomalyHalt

        rec = self.recovery
        for event, spec in pending:
            rule = spec.rule
            if spec.action == "skip":
                # Discard the just-applied update: restore the pre-step
                # snapshot — params, momentum, step count, AND the
                # error-feedback residual, bit-identical (donation is off
                # under recovery, so the buffers are intact).
                self.state, self.carry = prev_state, prev_carry
                rec.consecutive_skips += 1
                step = int(self.state.step)
                if self.goodput is not None:
                    # The discarded update's step time was NOT progress:
                    # reclassify it as wasted (nan_grad's designated
                    # badput).
                    self.goodput.wasted_step()
                rec.record("skip", step, rule,
                           consecutive=rec.consecutive_skips,
                           budget=spec.budget)
            elif spec.action == "rollback":
                if self._ckpt is None or self._ckpt.latest_step() is None:
                    self.logger.error(
                        "recovery: rollback for rule %s but no checkpoint "
                        "exists — escalating to halt", rule)
                    raise AnomalyHalt(event)
                uses = rec.rollback_uses.get(rule, 0)
                wait = spec.param * (2 ** uses)
                rec.rollback_uses[rule] = uses + 1
                if wait > 0:
                    time.sleep(wait)
                self.restore()
                step = int(self.state.step)
                if self.goodput is not None:
                    # restore() marked its own span ckpt (backoff sleep
                    # included); the rewound step's attribution becomes
                    # wasted work.
                    self.goodput.wasted_step()
                rec.record("rollback", step, rule, backoff_s=wait,
                           use=uses + 1, budget=spec.budget)
            elif spec.action == "degrade":
                if self._degraded:
                    continue
                if self._dense_step is None:
                    # Dense-allreduce fallback over the SAME state
                    # treedef: the always-dense branch of the compiled
                    # update (warmup_dense_steps=2**30).
                    self._dense_step = self._build_train_step(
                        tx=self._make_tx(warmup_dense_steps=1 << 30))
                self._train_step = self._dense_step
                self._degraded = True
                rec.degraded = True
                rec.degrade_episodes += 1
                self._degrade_until = step + int(spec.param)
                rec.record("degrade", step, rule,
                           until_step=self._degrade_until,
                           episode=rec.degrade_episodes,
                           budget=spec.budget)
        return step

    def finalize_resilience(self, status: str) -> None:
        """End-of-run summary record — what ``report recovery`` and the
        gate smoke's structural checks key on. No-op for runs with no
        resilience surface (keeps default metrics files byte-stable)."""
        if (self.injector is None and self.recovery is None
                and status == "completed"):
            return
        n = self.recovery.n_recoveries if self.recovery is not None else 0
        self.metrics.log(
            "recovery", flush=True, action="summary", final_status=status,
            completed=int(status == "completed"), n_recoveries=n,
            step=int(self.state.step))

    def _state_template(self):
        from jax.sharding import NamedSharding

        rep = NamedSharding(self.mesh, P())

        def leaf(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=rep)

        template = jax.tree.map(leaf, self.state)
        if self.p > 1:
            dp = NamedSharding(self.mesh, P("dp"))
            template = template._replace(opt_state=template.opt_state._replace(
                residual=jax.tree.map(
                    lambda r: jax.ShapeDtypeStruct(
                        r.shape, r.dtype, sharding=dp),
                    self.state.opt_state.residual)))
        return template


