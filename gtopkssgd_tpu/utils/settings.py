"""Global knobs + logging (reference L0: settings.py — module constants,
debug/profiling switches, logger creation).

The reference kept a module-level logger writing per-rank log files (the
launch scripts tee'd stdout per host). Here one helper builds a logger
tagged with the process index; everything else that was a settings.py
constant is an explicit dataclass/CLI flag in the trainer instead.
"""

from __future__ import annotations

import logging
import os
import sys

DEBUG = bool(int(os.environ.get("GTOPK_DEBUG", "0")))
# Flag-guarded per-step timing decomposition (reference profiling switch).
PROFILING = bool(int(os.environ.get("GTOPK_PROFILING", "1")))


def enable_compilation_cache(
    path: str | None = None,
) -> None:
    """Point jax at a persistent on-disk compilation cache so repeated
    CLI/benchmark invocations skip the 20-60 s XLA compiles (the driver
    runs bench.py cold every round). Override dir with GTOPK_JIT_CACHE;
    no-op if jax already has a cache configured."""
    import jax

    if jax.config.jax_compilation_cache_dir:
        return
    path = path or os.environ.get("GTOPK_JIT_CACHE",
                                  "/tmp/jax_cache_gtopkssgd")
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

def backend_responsive(timeout_s: float = 150.0) -> bool:
    """Can this process's jax backend initialize within ``timeout_s``?

    On a tunneled accelerator, backend init BLOCKS FOREVER inside PJRT
    client creation when the tunnel is down (observed: ``make_c_api_client``
    hung indefinitely after the relay died), so probing
    ``jax.device_count()`` in-process can hang the caller. The probe runs
    in a subprocess with a timeout instead. It replicates the parent's
    platform pin via the config API — the machine's sitecustomize overrides
    the ``JAX_PLATFORMS`` env var, so a CPU-pinned parent (tests, CI mesh)
    must not have its probe grab the exclusive-access real device.
    Importing jax does NOT initialize a backend; this helper is safe to
    call before any device use. Used by bench.py and
    __graft_entry__.dryrun_multichip so the hang-avoidance logic cannot
    drift between the two driver entry points.
    """
    import subprocess

    import jax

    plats = jax.config.jax_platforms
    pin = (f"jax.config.update('jax_platforms', {plats!r})\n"
           if plats else "")
    code = f"import jax\n{pin}print(jax.device_count())"
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, timeout=timeout_s)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


_FMT = "%(asctime)s [%(name)s:r{rank}] %(levelname)s %(message)s"


def get_logger(name: str = "gtopk", rank: int = 0,
               log_file: str | None = None) -> logging.Logger:
    logger = logging.getLogger(f"{name}.r{rank}")
    if logger.handlers:
        return logger
    logger.setLevel(logging.DEBUG if DEBUG else logging.INFO)
    fmt = logging.Formatter(_FMT.format(rank=rank), "%H:%M:%S")
    sh = logging.StreamHandler(sys.stdout)
    sh.setFormatter(fmt)
    logger.addHandler(sh)
    if log_file:
        fh = logging.FileHandler(log_file)
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    logger.propagate = False
    return logger
