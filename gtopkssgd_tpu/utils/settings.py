"""Global knobs + logging (reference L0: settings.py — module constants,
debug/profiling switches, logger creation).

The reference kept a module-level logger writing per-rank log files (the
launch scripts tee'd stdout per host). Here one helper builds a logger
tagged with the process index; everything else that was a settings.py
constant is an explicit dataclass/CLI flag in the trainer instead.
"""

from __future__ import annotations

import logging
import os
import sys

DEBUG = bool(int(os.environ.get("GTOPK_DEBUG", "0")))
# Flag-guarded per-step timing decomposition (reference profiling switch).
PROFILING = bool(int(os.environ.get("GTOPK_PROFILING", "1")))


def _default_cache_dir() -> str:
    """Repo-local (gitignored) compile-cache dir: /tmp is wiped between
    sessions on this machine, which re-pays every 20-60 s XLA compile;
    the repo checkout persists."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache")


def force_cpu_mesh(n: int = 8) -> None:
    """Force an n-device virtual CPU mesh for this process.

    This machine's sitecustomize registers the tunneled accelerator
    plugin at interpreter start and overrides ``JAX_PLATFORMS``, so an
    env-var-only ``JAX_PLATFORMS=cpu`` silently dials the tunnel — and
    blocks forever when it is down. The config API wins over both, and
    any inherited device-count flag is REPLACED (the parent may itself
    have been forced to a different count). Must run before the jax
    backend initializes; shared by tests/conftest.py and every CPU-mesh
    benchmark script so the workaround cannot drift."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    import jax

    jax.config.update("jax_platforms", "cpu")


def enable_compilation_cache(
    path: str | None = None,
) -> None:
    """Point jax at a persistent on-disk compilation cache so repeated
    CLI/benchmark invocations skip the 20-60 s XLA compiles (the driver
    runs bench.py cold every round). Override dir with GTOPK_JIT_CACHE;
    no-op if jax already has a cache configured."""
    import jax

    if jax.config.jax_compilation_cache_dir:
        return
    path = path or os.environ.get("GTOPK_JIT_CACHE", _default_cache_dir())
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def safe_donate(*argnums: int) -> tuple:
    """donate_argnums, except on XLA:CPU where it must be empty.

    Executing a persistent-cache-DESERIALIZED executable whose signature
    donates input buffers segfaults on XLA:CPU (jaxlib 0.4.x; reproduced
    with the gtopk train step — cold compile runs fine, the warm-cache
    run of the byte-identical program crashes at dispatch). Donation is
    purely a device-memory optimization, so dropping it on the virtual
    CPU mesh changes nothing observable; on TPU it stays, where the
    param+optimizer aliasing actually pays.
    """
    import jax

    return argnums if jax.default_backend() != "cpu" else ()


def init_backend_with_deadline(timeout_s: float = 150.0) -> bool:
    """Initialize THIS process's jax backend, but give up after a deadline.

    On a tunneled accelerator, backend init BLOCKS FOREVER inside PJRT
    client creation when the tunnel is down (observed: ``make_c_api_client``
    hung indefinitely after the relay died), so a bare
    ``jax.device_count()`` can hang the caller with no recourse. This runs
    the init on a daemon thread and waits up to ``timeout_s``:

      * already-initialized backend → returns True immediately (no cost,
        no contention — in particular no second process fighting the
        parent for an exclusive-access device, which a subprocess probe
        would);
      * healthy cold init → pays the one init the caller needed anyway;
      * init ERROR → returns True quickly; the caller's next jax call
        surfaces the real error text (not a misleading timeout message);
      * hung init → returns False at the deadline; the blocked daemon
        thread cannot be cancelled, so the caller should fall back to a
        path that avoids this backend (CPU re-exec) or exit promptly.

    Used by bench.py and __graft_entry__.dryrun_multichip so the
    hang-avoidance logic cannot drift between the two driver entry points.
    """
    import threading

    import jax

    done = threading.Event()

    def _init():
        try:
            jax.device_count()
        except Exception:
            pass  # caller's own jax use will raise the real error
        finally:
            done.set()

    threading.Thread(target=_init, daemon=True,
                     name="jax-backend-init-watchdog").start()
    return done.wait(timeout_s)


_FMT = "%(asctime)s [%(name)s:r{rank}] %(levelname)s %(message)s"


def get_logger(name: str = "gtopk", rank: int = 0,
               log_file: str | None = None) -> logging.Logger:
    logger = logging.getLogger(f"{name}.r{rank}")
    if logger.handlers:
        return logger
    logger.setLevel(logging.DEBUG if DEBUG else logging.INFO)
    fmt = logging.Formatter(_FMT.format(rank=rank), "%H:%M:%S")
    sh = logging.StreamHandler(sys.stdout)
    sh.setFormatter(fmt)
    logger.addHandler(sh)
    if log_file:
        fh = logging.FileHandler(log_file)
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    logger.propagate = False
    return logger
