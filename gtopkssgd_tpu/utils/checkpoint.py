"""Checkpoint/resume via Orbax (reference C1 saved only the model
state_dict at epoch boundaries and silently LOST the compressor residuals
on resume — SURVEY.md §5. Here the whole training state is one pytree, so
the error-feedback residual, momentum, and step count all survive a
restart; the trainer additionally fast-forwards the data stream to the
restored epoch's permutation — epoch-level granularity, matching the
epoch-boundary save cadence).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager for one state pytree.

    The state must be a pure pytree of arrays/scalars (the trainer's
    TrainState qualifies — residual included, since it lives in opt_state).
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        self._mgr.wait_until_finished()
        return saved

    def restore(self, state_template: Any, step: Optional[int] = None) -> Any:
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(state_template)
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def close(self) -> None:
        self._mgr.close()
