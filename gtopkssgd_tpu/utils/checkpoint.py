"""Checkpoint/resume via Orbax (reference C1 saved only the model
state_dict at epoch boundaries and silently LOST the compressor residuals
on resume — SURVEY.md §5. Here the whole training state is one pytree, so
the error-feedback residual, momentum, and step count all survive a
restart; the trainer additionally fast-forwards the data stream to the
restored position).

Integrity (resilience subsystem): every save writes a sidecar
``integrity-<step>.json`` next to orbax's step dir, recording the run's
config_hash (obs/manifest.py — the same hash the run manifest carries)
and a digest of the state treedef + per-leaf shapes/dtypes. restore()
verifies both BEFORE handing bytes to orbax:

  config_hash mismatch  -> CheckpointMismatch (refused: resuming a run
                           under different flags silently changes the
                           experiment; ``allow_mismatch`` is the
                           explicit escape hatch, mirroring the fleet
                           merger's --allow-mismatch)
  digest mismatch       -> CheckpointMismatch (the state structure
                           changed — e.g. obs_layers toggled — and an
                           orbax restore into the wrong treedef would
                           fail later and worse)
  corrupt/unreadable    -> fall back to the PREVIOUS step (a machine
                           killed mid-save leaves a torn latest; losing
                           one save interval beats losing the run)

A checkpoint with no sidecar (written before this subsystem) restores
with a warning — old runs stay resumable.
"""

from __future__ import annotations

import json
import hashlib
import os
from typing import Any, List, Optional

import orbax.checkpoint as ocp


class CheckpointMismatch(RuntimeError):
    """Refusal to restore a checkpoint whose recorded config_hash or
    state digest disagrees with the restoring run's."""


def state_digest(state: Any) -> str:
    """Short digest of a pytree's STRUCTURE (treedef + per-leaf
    shape/dtype): two states with equal digests are restore-compatible.
    Works on concrete arrays and ShapeDtypeStruct templates alike."""
    import jax

    leaves, treedef = jax.tree.flatten(state)
    blob = json.dumps([str(treedef)] + [
        [list(getattr(x, "shape", ())), str(getattr(x, "dtype", type(x)))]
        for x in leaves
    ])
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class CheckpointManager:
    """Orbax CheckpointManager wrapper for one state pytree, plus the
    integrity sidecars described in the module docstring.

    The state must be a pure pytree of arrays/scalars (the trainer's
    TrainState qualifies — residual included, since it lives in opt_state).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 config_hash: Optional[str] = None, logger=None):
        self.directory = os.path.abspath(directory)
        self.config_hash = config_hash
        self.logger = logger
        self.last_restored_step: Optional[int] = None
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    # --------------------------------------------------------- integrity
    def _integrity_path(self, step: int) -> str:
        return os.path.join(self.directory, f"integrity-{step}.json")

    def _write_integrity(self, step: int, state: Any,
                         meta: Optional[dict] = None) -> None:
        rec = {
            "step": int(step),
            "config_hash": self.config_hash,
            "state_digest": state_digest(state),
        }
        if meta:
            # Small json-able facts about the SAVED state that a
            # restoring run needs before it can build a template — e.g.
            # the elastic resize path records residual_p, the partition
            # width of the per-device residual, so a different-P resume
            # knows the old shape without guessing.
            rec["meta"] = dict(meta)
        path = self._integrity_path(step)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(rec, fh, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, path)  # atomic: no torn sidecars

    def _read_integrity(self, step: int) -> Optional[dict]:
        try:
            with open(self._integrity_path(step)) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _prune_integrity(self) -> None:
        """Drop sidecars whose step orbax already garbage-collected
        (max_to_keep), so the directory stays in lockstep."""
        live = set(self.all_steps())
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if not (name.startswith("integrity-")
                    and name.endswith(".json")):
                continue
            stem = name[len("integrity-"):-len(".json")]
            if stem.isdigit() and int(stem) not in live:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    def _verify(self, step: int, state_template: Any,
                allow_mismatch: bool) -> None:
        rec = self._read_integrity(step)
        if rec is None:
            if self.logger is not None:
                self.logger.warning(
                    "checkpoint step %d has no integrity sidecar "
                    "(pre-resilience save); restoring unverified", step)
            return
        problems: List[str] = []
        want_hash = rec.get("config_hash")
        if (want_hash is not None and self.config_hash is not None
                and want_hash != self.config_hash):
            problems.append(
                f"config_hash {want_hash} != this run's "
                f"{self.config_hash} (different flags)")
        want_digest = rec.get("state_digest")
        have_digest = state_digest(state_template)
        if want_digest is not None and want_digest != have_digest:
            problems.append(
                f"state digest {want_digest} != template {have_digest} "
                "(state treedef/shape change)")
        if not problems:
            return
        msg = (f"checkpoint step {step} in {self.directory} does not "
               f"match this run: " + "; ".join(problems))
        if allow_mismatch:
            if self.logger is not None:
                self.logger.warning("%s — restoring anyway "
                                    "(--allow-ckpt-mismatch)", msg)
            return
        raise CheckpointMismatch(
            msg + " (pass --allow-ckpt-mismatch to override)")

    def sidecar_meta(self, step: Optional[int] = None) -> dict:
        """The ``meta`` dict saved alongside ``step`` (default: latest
        step); {} when the step has no sidecar or the sidecar predates
        the meta channel."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return {}
        rec = self._read_integrity(int(step))
        meta = rec.get("meta") if rec else None
        return dict(meta) if isinstance(meta, dict) else {}

    # ------------------------------------------------------ save/restore
    def save(self, step: int, state: Any, *, force: bool = False,
             meta: Optional[dict] = None) -> bool:
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        self._mgr.wait_until_finished()
        if saved:
            self._write_integrity(step, state, meta=meta)
            self._prune_integrity()
        return saved

    def restore(self, state_template: Any, step: Optional[int] = None,
                allow_mismatch: bool = False) -> Any:
        """Restore ``step`` (default: latest), verifying integrity first
        and falling back step-by-step past CORRUPT checkpoints. Mismatch
        refusals (CheckpointMismatch) never fall back — every step of a
        dir shares one run config, so an older step cannot fix it."""
        if step is not None:
            candidates = [step]
        else:
            candidates = sorted(self.all_steps(), reverse=True)
        if not candidates:
            return None
        last_err: Optional[Exception] = None
        for s in candidates:
            self._verify(s, state_template, allow_mismatch)
            try:
                state = self._mgr.restore(
                    s, args=ocp.args.StandardRestore(state_template)
                )
            except Exception as e:  # torn/corrupt step: try the previous
                last_err = e
                if self.logger is not None:
                    self.logger.warning(
                        "checkpoint step %d unreadable (%s: %s); falling "
                        "back to the previous step", s, type(e).__name__,
                        str(e)[:200])
                continue
            self.last_restored_step = int(s)
            if self.logger is not None and s != candidates[0]:
                self.logger.warning(
                    "restored FALLBACK step %d (latest step %d was "
                    "corrupt)", s, candidates[0])
            return state
        raise RuntimeError(
            f"no restorable checkpoint in {self.directory} "
            f"(tried steps {candidates})") from last_err

    def all_steps(self) -> List[int]:
        return sorted(int(s) for s in self._mgr.all_steps())

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def close(self) -> None:
        self._mgr.close()
