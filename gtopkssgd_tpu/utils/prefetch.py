"""Background host-batch prefetcher (reference C8 parity: torch
DataLoader's worker processes overlapped batch assembly + augmentation
with GPU compute; here ONE daemon thread overlaps numpy batch assembly —
including the C++ augment loops, which release the GIL inside
native.dataprep — with the device step).

Design constraints honored:

  * Determinism: a single worker thread pulls from the underlying
    iterators strictly in order, so the batch stream is identical to the
    synchronous path (tested).
  * JAX single-threaded discipline: the worker touches ONLY numpy/host
    code; `jax.device_put` stays on the consumer thread.
  * Failure transparency: an exception in assembly is captured and
    re-raised at the consumer's next __next__, not swallowed.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable


class Prefetcher:
    """Wraps a zero-arg `produce` callable (returns the next host batch)
    with a bounded background queue of `depth` pre-assembled batches."""

    _STOP = object()

    def __init__(self, produce: Callable[[], object], depth: int = 2):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._produce = produce
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                item = self._produce()
            except BaseException as e:  # propagate to the consumer
                self._err = e
                self._q.put(self._STOP)
                return
            # Bounded put that stays responsive to close()
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            # close() ran (nothing else sets _stop): the worker is gone
            # and the queue drained; blocking on get() would hang forever.
            raise RuntimeError("prefetcher is closed")
        if self._err is not None:
            # Worker already died; fail every subsequent call instead of
            # blocking forever on a queue that will never be fed again.
            raise RuntimeError("prefetch worker failed") from self._err
        item = self._q.get()
        if item is self._STOP:
            raise RuntimeError("prefetch worker failed") from self._err
        return item

    def close(self):
        """Stop the worker and discard queued batches (used when the
        underlying iterators are re-created, e.g. on checkpoint restore).

        Raises if the worker cannot be joined: returning with the thread
        still alive would let a replacement prefetcher race it on the
        same underlying iterators (generators are not thread-safe).
        """
        self._stop.set()
        # drain so a blocked put wakes up
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=60.0)
        if self._thread.is_alive():
            raise RuntimeError(
                "prefetch worker did not stop within 60 s; "
                "refusing to hand its iterators to a replacement"
            )
