"""Structured metrics (upgrade over the reference's text-only logging).

The reference's de-facto metrics pipeline was parsing per-rank text logs
(SURVEY.md §5). Here every record is appended as one JSON line to
``metrics.jsonl`` AND logged as the familiar human-readable line, so both
machine analysis and eyeballs work.

``MetricsLogger`` is a context manager; owners that cannot use ``with``
(the Trainer holds one for its whole lifetime) call ``close()`` from their
own ``__exit__``. The file is opened line-buffered, so every completed
record hits the OS on its own ``write`` — a run killed mid-step (the stall
watchdog hard-exits, the kernel OOM-kills) loses at most the line being
written, without paying an explicit ``flush()`` syscall per record.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    def __init__(self, out_dir: Optional[str] = None,
                 logger: Optional[logging.Logger] = None, rank: int = 0):
        self.logger = logger
        self.rank = rank
        self._fh = None
        if out_dir is not None and rank == 0:
            os.makedirs(out_dir, exist_ok=True)
            self._fh = open(os.path.join(out_dir, "metrics.jsonl"), "a",
                            buffering=1)

    def log(self, kind: str, *, flush: bool = False,
            **fields: Any) -> Dict[str, Any]:
        """``flush=True`` fsyncs the record to disk before returning —
        for diagnostics that must survive a hard kill in the very next
        instruction (anomaly ``event`` records, the manifest header);
        line buffering alone only guarantees the write reaches the OS."""
        if not isinstance(kind, str) or not kind:
            raise ValueError(
                f"metrics kind must be a non-empty str, got {kind!r}")
        rec = {"kind": kind, "time": time.time(), "rank": self.rank, **fields}
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
            if flush:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except OSError:
                    pass
        if self.logger is not None:
            human = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in fields.items()
            )
            self.logger.info("[%s] %s", kind, human)
        return rec

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
