"""Structured metrics (upgrade over the reference's text-only logging).

The reference's de-facto metrics pipeline was parsing per-rank text logs
(SURVEY.md §5). Here every record is appended as one JSON line to
``metrics.jsonl`` AND logged as the familiar human-readable line, so both
machine analysis and eyeballs work.

``MetricsLogger`` is a context manager; owners that cannot use ``with``
(the Trainer holds one for its whole lifetime) call ``close()`` from their
own ``__exit__``. The file is opened line-buffered, so every completed
record hits the OS on its own ``write`` — a run killed mid-step (the stall
watchdog hard-exits, the kernel OOM-kills) loses at most the line being
written, without paying an explicit ``flush()`` syscall per record.

Multi-process runs (``shard=True``): EVERY process writes its own shard
with the deterministic name ``metrics.rank{r}.jsonl`` in the same out
dir, so cross-host comparison is possible at all — the fleet layer
(obs/fleet.py) merges shards by (kind, step) and validates via each
shard's manifest header that they belong to the same run. Single-process
runs keep the classic ``metrics.jsonl`` (rank 0 only).
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import Any, Callable, Dict, Optional

# Registered record kinds. Shared with the report CLI (which flags
# unregistered kinds in a run) and enforced at log() time, so a typo'd
# kind fails loudly instead of silently vanishing from every report;
# graftlint's metric-kind rule (gtopkssgd_tpu/analysis) additionally
# resolves every static `.log(...)` call site against this set, so a
# typo is caught before any run.
KINDS = frozenset({
    "manifest",    # run provenance header (obs/manifest.py), first record
    "train",       # per-log-interval training stats
    "eval",        # validation metrics
    "epoch",       # end-of-epoch combined stats
    "obs",         # on-device compression/comm counters (obs/counters.py)
    "layers",      # per-layer telemetry, one record per layer per obs step
    "spans",       # Tracer window means (obs/tracing.py flush)
    "span",        # Tracer per-span record (record_each=True)
    "event",       # anomaly events (obs/events.py)
    "stall",       # watchdog stall diagnostic (obs/watchdog.py)
    "attr",        # T_compute/T_select/T_comm split (obs/trace_attr.py)
    "attr_error",  # attribution capture failure (gate smoke)
    "fleet",       # cross-rank merged per-step stats (obs/fleet.py)
    "ledger",      # predicted-vs-measured comm model rows (obs/ledger.py)
    "inject",      # injected-fault firings (resilience/inject.py)
    "recovery",    # recovery actions + end-of-run summary
                   # (resilience/policy.py, trainer emergency save)
    "twostage",    # twostage-vs-exact A/B evidence row (gate smoke):
                   # audit recall + T_select fractions for both methods
    "codec",       # wire-codec A/B evidence row (gate smoke): measured
                   # int8-vs-fp32 wire-bytes ratios, ledger audit, recall
    "lint",        # graftlint summary row (gate smoke): finding counts
                   # from python -m gtopkssgd_tpu.analysis, gated at 0
                   # non-baselined findings
    "plan",        # comm-planner decision (parallel/planner.py): chosen
                   # wire plan + every candidate's modeled score; also
                   # the gate smoke's balanced-vs-tree A/B evidence row
    "bucket",      # gradient-bucketing evidence row (parallel/bucketing):
                   # trainer logs the chosen BucketPlan (boundaries,
                   # per-bucket k, modeled ms for B in {1, chosen, L});
                   # the gate smoke logs the bucketed-vs-leafwise A/B
                   # (collective-count ratio, audited recall, bytes ratio)
    "calib",       # live comm-model refit (obs/calib.py): fitted
                   # alpha/beta, residual spread, n_samples, drift vs
                   # the planner's committed inputs and the startup fit
    "regress",     # cross-run regression evidence row (gate smoke):
                   # registry regress exit codes + fitted-vs-true check
                   # against obs/registry.py's runs.jsonl baseline
    "overlap",     # pipelined-vs-serial A/B evidence row (gate smoke):
                   # bit-identity deltas, measured overlap_frac from the
                   # trace capture, and the DP's B>1 crossover pin
    "compile",     # compile-plane accounting (obs/memwatch.py): one
                   # record per distinct dispatch shape (cost/memory
                   # analysis + lower/compile wall times) and one per
                   # executable-cache growth (recompile), fsync'd
    "mem",         # sampled live-memory window (obs/memwatch.py):
                   # live_arrays count/bytes by dtype + per-device
                   # memory_stats where the backend exposes them
    "critpath",    # per-step stage-interval record (obs/critpath.py):
                   # ordered {stage, t0_us, t1_us} segments with the
                   # comm span split into wire vs skew-wait by the
                   # ledger's alpha-beta model; fleet joins these
                   # across ranks into the global critical path
    "goodput",     # cumulative goodput/badput decomposition
                   # (obs/goodput.py): per-category seconds summing to
                   # measured wall (conservation), goodput_frac /
                   # other_frac, fsync'd every N steps + final summary
    "linkmap",     # per-(axis, peer) link weather map (obs/linkmap.py):
                   # one snapshot per calibrator capture with every
                   # link's EWMA latency/bandwidth, the carved
                   # per-round intervals, and the worst-link summary;
                   # fsync'd — written BEFORE the link_degraded rule
                   # can halt the run
    "resize",      # elastic fleet resize (resilience/elastic.py): one
                   # fsync'd record per resize decision — old_p, new_p,
                   # reason (preempt|evict|inject), evicted_ranks,
                   # drained_step, restore_step, lineage_id,
                   # resize_epoch — durable BEFORE any process exits 46
    "forecast",    # scale-out forecast record (obs/forecast.py): the
                   # hindcast error (predicted vs measured step time on
                   # THIS run), the per-P-target recommendation grid
                   # with resid-derived uncertainty bands, and the
                   # tree->balanced crossover P; fsync'd — written
                   # BEFORE the forecast_drift rule can halt the run
})

_SHARD_RE = re.compile(r"^metrics\.rank(\d+)\.jsonl$")


def shard_filename(rank: int) -> str:
    """Deterministic per-rank shard name; the join key the fleet merger
    (and a human with `ls`) recovers the rank from."""
    return f"metrics.rank{rank}.jsonl"


def shard_rank(path: str) -> Optional[int]:
    """Rank encoded in a shard filename, or None for non-shard names."""
    m = _SHARD_RE.match(os.path.basename(path))
    return int(m.group(1)) if m else None


class MetricsLogger:
    def __init__(self, out_dir: Optional[str] = None,
                 logger: Optional[logging.Logger] = None, rank: int = 0,
                 shard: bool = False,
                 sink: Optional[Callable[[Dict[str, Any]], None]] = None):
        """``shard=True`` (multi-process runs) writes
        ``metrics.rank{rank}.jsonl`` on EVERY rank; the default writes
        ``metrics.jsonl`` on rank 0 only. ``sink`` is called with each
        completed record (file or no file) — the live exporter's hook
        (obs.exporter.MetricsExporter.observe matches it); sink errors
        are swallowed so export can never take down training."""
        self.logger = logger
        self.rank = rank
        self.sink = sink
        self._fh = None
        if out_dir is not None and (shard or rank == 0):
            os.makedirs(out_dir, exist_ok=True)
            name = shard_filename(rank) if shard else "metrics.jsonl"
            self._fh = open(os.path.join(out_dir, name), "a", buffering=1)

    def log(self, kind: str, *, flush: bool = False,
            **fields: Any) -> Dict[str, Any]:
        """``flush=True`` fsyncs the record to disk before returning —
        for diagnostics that must survive a hard kill in the very next
        instruction (anomaly ``event`` records, the manifest header);
        line buffering alone only guarantees the write reaches the OS."""
        if not isinstance(kind, str) or not kind:
            raise ValueError(
                f"metrics kind must be a non-empty str, got {kind!r}")
        if kind not in KINDS:
            raise ValueError(
                f"unregistered metrics kind {kind!r}; add it to "
                f"utils.metrics.KINDS (registered: {sorted(KINDS)})")
        rec = {"kind": kind, "time": time.time(), "rank": self.rank, **fields}
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
            if flush:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except OSError:
                    pass
        if self.sink is not None:
            try:
                self.sink(rec)
            except Exception:
                pass
        if self.logger is not None:
            human = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in fields.items()
            )
            self.logger.info("[%s] %s", kind, human)
        return rec

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
