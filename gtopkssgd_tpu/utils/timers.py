"""Per-step timing decomposition (reference L0: the wall-clock timer dicts
in utils.py / the profiling switch in settings.py).

The reference accumulated forward/backward/compression/communication times
into dicts and logged them every N iterations — that decomposition is the
paper's own analysis axis. Here the same split, plus `jax.block_until_ready`
fencing so the async dispatch queue doesn't fold every phase into the last.

For phases fused inside one jitted step (the production path — XLA overlaps
comm and compute, so a host-side timer *cannot* see them separately), use
the benchmark harness's segmented mode which jits each phase apart; this
timer then reports whole-step time under 'step'.
"""

from __future__ import annotations

import collections
import time
from contextlib import contextmanager
from typing import Dict

import jax

PHASES = ("io", "forward", "backward", "compress", "comm", "update", "step")


class TimingStats:
    """Accumulates per-phase seconds; reference utils.py's timer-dict shape."""

    def __init__(self):
        self.totals: Dict[str, float] = collections.defaultdict(float)
        self.counts: Dict[str, int] = collections.defaultdict(int)

    def add(self, phase: str, seconds: float) -> None:
        self.totals[phase] += seconds
        self.counts[phase] += 1

    def mean(self, phase: str) -> float:
        c = self.counts[phase]
        return self.totals[phase] / c if c else 0.0

    def summary(self) -> Dict[str, float]:
        return {p: self.mean(p) for p in self.totals}

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()


class StepTimer:
    """Context-manager timer: ``with timer('forward'): ...``.

    ``sync=True`` (default) blocks on JAX's async queue before reading the
    clock, so the phase really finished; pass sync=False for host-only
    phases like data loading.
    """

    def __init__(self, stats: TimingStats | None = None):
        self.stats = stats or TimingStats()

    @contextmanager
    def __call__(self, phase: str, *, sync: bool = True, value=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync:
                if value is not None:
                    jax.block_until_ready(value)
                else:
                    jax.effects_barrier()
            self.stats.add(phase, time.perf_counter() - t0)
