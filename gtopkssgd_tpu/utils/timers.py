"""Per-step timing decomposition (reference L0: the wall-clock timer dicts
in utils.py / the profiling switch in settings.py).

The reference accumulated forward/backward/compression/communication times
into dicts and logged them every N iterations — that decomposition is the
paper's own analysis axis. Here the same split, plus `jax.block_until_ready`
fencing so the async dispatch queue doesn't fold every phase into the last.

For phases fused inside one jitted step (the production path — XLA overlaps
comm and compute, so a host-side timer *cannot* see them separately), use
the benchmark harness's segmented mode which jits each phase apart; this
timer then reports whole-step time under 'step'.

Instrumentation call sites (trainer/benchmark phase timing) live in
``gtopkssgd_tpu.obs.tracing.Tracer``, which builds on TimingStats and adds
nested span paths plus ``jax.profiler.TraceAnnotation`` scopes; StepTimer
stays as the minimal primitive for harness-internal timing.
"""

from __future__ import annotations

import collections
import time
from contextlib import contextmanager
from typing import Dict

import jax

PHASES = ("io", "forward", "backward", "compress", "comm", "update", "step")


def true_sync(tree) -> None:
    """Block until every queued computation feeding ``tree`` has executed.

    ``jax.block_until_ready`` is NOT sufficient on remote-tunneled
    platforms: the 'axon' TPU proxy acks readiness before device execution
    completes (measured: a 1.1 TFLOP matmul "ready" in 27us, i.e. 40
    EFLOP/s — impossible), while a device-to-host read takes the honest
    round trip. A D2H read of one element cannot return before the
    executable that produced its buffer ran, and XLA executables run whole-
    program, so one element of the LAST output in a dependency chain fences
    the entire chain. Cost: one tunnel round trip (~66 ms here) — charge it
    once per timing window, never per step.
    """
    leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "ravel")]
    if not leaves:
        return
    leaf = leaves[0]
    jax.device_get(leaf.ravel()[0:1] if leaf.size else leaf)


def sync_round_trip_seconds(tree) -> float:
    """Measured cost of one true_sync on already-materialized data — the
    fixed host<->device round trip a timing window should subtract."""
    true_sync(tree)  # materialize
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        true_sync(tree)
        best = min(best, time.perf_counter() - t0)
    return best


def timed_window(run_chunk, rtt: float, min_seconds: float = 0.5,
                 initial_steps: int = 8):
    """The one honest timing loop (round-1 lesson — keep it in ONE place).

    ``run_chunk(steps)`` must dispatch `steps` calls back-to-back and fence
    with true_sync. The window grows geometrically until it exceeds both
    ``min_seconds`` and 20x the sync round trip, so tiny ops aren't drowned
    in fixed sync overhead; the final window's single round trip is
    subtracted. Returns (seconds_per_step, steps_timed).
    """
    floor = max(min_seconds, 20 * rtt)
    steps = initial_steps
    while True:
        t0 = time.perf_counter()
        run_chunk(steps)
        elapsed = time.perf_counter() - t0
        if elapsed >= floor:
            return max(elapsed - rtt, 1e-9) / steps, steps
        steps = int(steps * min(
            10.0, max(2.0, 1.25 * floor / max(elapsed, 1e-4)))) + 1


class TimingStats:
    """Accumulates per-phase seconds; reference utils.py's timer-dict shape."""

    def __init__(self):
        self.totals: Dict[str, float] = collections.defaultdict(float)
        self.counts: Dict[str, int] = collections.defaultdict(int)

    def add(self, phase: str, seconds: float) -> None:
        self.totals[phase] += seconds
        self.counts[phase] += 1

    def mean(self, phase: str) -> float:
        c = self.counts[phase]
        return self.totals[phase] / c if c else 0.0

    def summary(self) -> Dict[str, float]:
        return {p: self.mean(p) for p in self.totals}

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()


class StepTimer:
    """Context-manager timer: ``with timer('forward'): ...``.

    ``sync=True`` (default) blocks on JAX's async queue before reading the
    clock, so the phase really finished; pass sync=False for host-only
    phases like data loading.
    """

    def __init__(self, stats: TimingStats | None = None):
        self.stats = stats or TimingStats()

    @contextmanager
    def __call__(self, phase: str, *, sync: bool = True, value=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync:
                if value is not None:
                    jax.block_until_ready(value)
                else:
                    jax.effects_barrier()
            self.stats.add(phase, time.perf_counter() - t0)
