"""Support layer (reference L0: settings.py + utils.py — flags, logger,
timer dicts, log accumulators) plus what the reference lacked: structured
metrics and real checkpointing.
"""

from gtopkssgd_tpu.utils.timers import (
    StepTimer,
    TimingStats,
    sync_round_trip_seconds,
    timed_window,
    true_sync,
)
from gtopkssgd_tpu.utils.metrics import MetricsLogger
from gtopkssgd_tpu.utils.checkpoint import CheckpointManager
from gtopkssgd_tpu.utils.settings import (
    enable_compilation_cache,
    force_cpu_mesh,
    get_logger,
    init_backend_with_deadline,
    safe_donate,
)
from gtopkssgd_tpu.utils.prefetch import Prefetcher

__all__ = [
    "StepTimer",
    "TimingStats",
    "sync_round_trip_seconds",
    "timed_window",
    "true_sync",
    "MetricsLogger",
    "CheckpointManager",
    "get_logger",
    "enable_compilation_cache",
    "force_cpu_mesh",
    "init_backend_with_deadline",
    "safe_donate",
    "Prefetcher",
]
