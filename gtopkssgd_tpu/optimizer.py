"""Distributed gTop-k optimizer — the reference's L2 layer, TPU-native.

Reference parity (SURVEY.md C3: the Horovod-style ``DistributedOptimizer``
wrapper in hclhkbu/gtopkssgd, living in/near dist_trainer.py): intercept the
gradients after backward, flatten/merge every layer's grad into ONE vector,
hand it to the compressor + allreducer, then apply the reduced sparse update
with SGD (momentum + weight decay) identically on every rank.

TPU-native redesign (SURVEY.md §7): instead of an object wrapping a stateful
optimizer plus a background communication thread, the whole pipeline is a
pure optax ``GradientTransformation``:

    (grads, state, params) -> (updates, state')

whose state carries the error-feedback residual as an ordinary array. One
jitted SPMD train step contains compute, compression, and the collective;
XLA overlaps them and Orbax checkpoints the residual for free (the reference
silently dropped residuals on resume — a sharp edge fixed here).

Pipeline inside ``update`` (names match the reference call stack, SURVEY.md
§3.1):

    flat            = ravel_pytree(grads)                 # "flatten/merge"
    flat            = clip_by_global_norm(flat)           # LSTM path: clip
                                                          #   BEFORE compress
    acc             = flat + residual                     # error feedback
    vals, idx, res  = compressor.compress(acc)            # local top-k
    global set      = sparse_allreduce(mode, ...)         # gtopk tree /
                                                          #   allgather / psum
    res'            = repair(res, vals, idx, gidx)        # add_residuals
    dense update    = scatter(global set) / P             # average
    updates         = SGD(momentum, wd) on dense update   # inner optimizer
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.flatten_util import ravel_pytree

from gtopkssgd_tpu.compression import get_compressor
from gtopkssgd_tpu.obs import counters as obs_counters
from gtopkssgd_tpu.modes import (
    ALL_MODES,
    DENSE_MODES,
    HIER_MODES,
    LAYERWISE_MODES,
)
from gtopkssgd_tpu.ops import (
    k_for_density,
    membership_mask,
    scatter_add_dense,
    select_topk,
    topk_abs,
)
from gtopkssgd_tpu.parallel import (
    get_codec, ici_dense_psum, parse_buckets, plan_buckets, resolve_plan,
    roundtrip_aligned, sparse_allreduce, validate_pin)
from gtopkssgd_tpu.parallel.bucketing import buckets_key, parse_pipeline

Array = jax.Array
ScalarOrSchedule = Union[float, Callable[[Array], Array]]


class GTopKSGDState(NamedTuple):
    """State pytree of the distributed optimizer. ``residual`` holds the
    per-device local compression state — checkpointing this state therefore
    preserves error feedback across resume. Its shape depends on the mode:
    a flat f32[N] error-feedback buffer (empty for the dense path); a tuple
    of per-leaf buffers for ``gtopk_layerwise``; and with
    ``momentum_correction`` a dict ``{"v": <buffer>, "u": <velocity>}``
    where v is the accumulated-velocity residual DGC selects from and u is
    the local momentum buffer (same flat/per-leaf shape as v). Every
    consumer (trainer shard_map strip/restore, per-device expansion, the
    checkpoint template) tree-maps over the field, so all three layouts
    ride the same plumbing.

    ``telemetry`` (obs subsystem, default off -> an empty pytree) carries
    the on-device training-health counters of the step that PRODUCED this
    state (obs.counters: achieved density, tau, residual norm, grad
    norms, wire bytes, mass-capture ratio) — f32 scalars, replicated
    under shard_map (the optimizer pmeans them), so the host can read
    them without touching per-device state. With ``telemetry_layers``
    it additionally holds ``"layers"`` (obs.counters.LAYER_FIELDS as
    f32[L] arrays, leaf order = jax.tree flatten order of the grads)
    and ``"age"`` (per-coordinate steps-since-last-shipped, residual
    layout, replicated by construction); with
    ``telemetry_audit_interval`` an ``"audit_recall"`` scalar (-1 =
    never audited)."""

    count: Array
    residual: Array
    inner: optax.OptState
    telemetry: Any = ()


def gtopk_sgd(
    learning_rate: ScalarOrSchedule,
    *,
    momentum: float = 0.9,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    compression: Optional[str] = "gtopk",
    density: float = 0.001,
    topk_method: str = "auto",
    clip_grad_norm: Optional[float] = None,
    axis_name: Optional[str] = "dp",
    axis_size: Optional[int] = None,
    hier_ici_size: int = 1,
    wire_codec: str = "fp32",
    comm_plan: Optional[str] = "auto",
    buckets: Union[str, int] = "concat",
    pipeline: str = "serial",
    warmup_dense_steps: int = 0,
    momentum_correction: bool = False,
    telemetry: bool = False,
    telemetry_layers: bool = False,
    telemetry_audit_interval: int = 0,
    _restore_rejected_u: bool = False,
) -> optax.GradientTransformation:
    """Build the distributed gTop-k S-SGD gradient transformation.

    Args mirror the reference's trainer/driver flags: ``learning_rate``
    (float or optax schedule), ``momentum``/``weight_decay``/``nesterov``
    (torch.optim.SGD semantics: wd is added to the *dense* averaged gradient
    before the momentum buffer, exactly like the reference where torch's SGD
    sees the sparse global update but decays every parameter), ``compression``
    + ``density`` (--compression/--density), ``clip_grad_norm`` (the LSTM
    paths clip BEFORE compression — SURVEY.md §3.4), and the mesh axis the
    collective runs over.

    With ``axis_name=None`` no collective is issued: this is the
    single-worker ``dl_trainer.py`` path — compression still runs so a
    1-device density sweep exercises error feedback.

    With ``axis_name`` set, ``update`` must run inside ``jax.shard_map``
    over that axis (the trainer does this for you). The actual axis size is
    derived from the bound mesh axis at trace time (``lax.axis_size``), so it
    cannot silently disagree with the mesh; ``axis_size``, if given, is only
    validated against it.

    ``warmup_dense_steps`` (reference C6 parity: the warm-up trick in
    settings.py — DGC-lineage "warm-up training", arXiv:1712.01887 §3)
    communicates the DENSE averaged gradient for the first W optimizer
    steps of a sparse mode, then switches to the sparse pipeline. Top-k
    at rho=0.001 updates only k coordinates per step, so cold-starting
    sparse costs a long accuracy ramp (measured: an 8-way gtopk run at
    600 steps trails dense 2.0-vs-0.2 in loss purely from the ramp); a
    few dense epochs remove it. Implemented as a ``lax.cond`` on the step
    counter INSIDE the one jitted update, so state shapes are identical
    in both phases, there is no recompile at the boundary, and
    checkpoint/resume lands in the right phase automatically. The
    residual passes through the dense phase unchanged (zeros), so error
    feedback starts exactly at the switch.

    ``compression='gtopk_layerwise'`` (TPU extension, arXiv:1911.08772
    layer-wise-top-k lineage — not reference parity; the reference always
    flattens, SURVEY.md §3.1) keeps error feedback and selection PER
    LAYER: residual is a pytree of per-leaf flat buffers, each leaf
    selects its own top-``ceil(rho * n_leaf)``, and only the concatenated
    (vals, idx) sets — k elements, not N — ever exist in the flat index
    space. The flat [N] gradient is never materialized, so each leaf's
    accumulate/select/zero-out chain can fuse into that leaf's backward
    epilogue instead of serializing behind a whole-model concatenation
    (the measured single-chip cost of the flat path —
    benchmarks/results/fused_variants_TPU_v5_lite.json). The collective
    is the unchanged gTop-k hypercube over the concatenated set, so the
    COMMUNICATED set is still a global magnitude top-K of the union;
    only the local per-device selection is layer-balanced.

    ``compression='gtopk_hier'`` enables the two-level TPU-idiom reduction
    (not reference parity — SURVEY.md §5 design option): the raw gradient is
    first dense-psum'd WITHIN each contiguous block of ``hier_ici_size``
    devices (an ICI slice — cheap, high-bandwidth links), then error
    feedback + top-k run on the slice-summed gradient and the gTop-k
    hypercube runs only ACROSS the ``P / hier_ici_size`` slices (the DCN
    hop, where sparsity pays). Every device of a slice computes identical
    sets, so the per-device residual stays consistent automatically.

    ``wire_codec`` (parallel.codec grammar: ``fp32 | int8[:BLOCK] |
    fp8[:BLOCK]``) selects the on-wire encoding of every sparse exchange.
    With a lossy codec the shipped values are requantized BEFORE the
    collective (``roundtrip_aligned``) and the quantization error
    ``vals - dequant(quant(vals))`` folds into the error-feedback
    residual right here at the compression layer, so codec error is
    self-correcting exactly like selection error; the collective then
    transports bits that decode to precisely the values selection was
    told were sent. Intermediate merge rounds requantize partial sums —
    that second-order error is shared bitwise-identically by all ranks
    (codec determinism) and is NOT residual-fed.

    ``buckets`` (layerwise only; parallel.bucketing grammar ``concat |
    leaf | <int B> | auto``) sets the MERGE GRANULARITY of the layerwise
    path. The historical default ``concat`` keeps today's exact wire:
    per-leaf selection, ONE merge over the concatenated set in the
    global index space. Any other spec switches to the bucketed
    pipeline: the leaves are partitioned into B contiguous byte-balanced
    buckets (the alpha-beta DP of parallel.bucketing — ``auto`` also
    chooses B, a pinned int or ``leaf`` fixes it), each bucket's (grad,
    residual) leaves concatenate into one flat operand, selection runs
    ONCE per bucket (k_b = ceil(density * n_b), the same fused two-stage
    kernels as everywhere else), and each bucket runs its own
    codec-framed merge in its BUCKET-LOCAL index space — B collectives
    per step instead of one, each cheaper in latency-critical regimes
    than L per-leaf merges and each with a smaller Elias-Fano index
    space than the global merge. The reduced update and the
    error-feedback residual scatter back to leaves through static bucket
    offsets, so the state layout (per-leaf residual tuple) and
    checkpoint treedef are identical to ``concat``. ``leaf`` (B = L) is
    per-leaf selection AND per-leaf merges — the fully-layerwise end;
    ``auto`` at B=1 is bit-identical to the flat ``gtopk`` pipeline over
    the raveled model (same k: ceil(density * N)).

    ``pipeline`` (bucketed layerwise only; parallel.bucketing grammar
    ``serial | overlap | auto``) sets the EXECUTION ORDER of the B
    select/merge stages within one step. ``serial`` is the paper's
    strictly sequential T_select + T_comm: bucket b+1's selection is
    gated (``lax.optimization_barrier``) on bucket b's merge outputs,
    so exactly one stage runs at a time — the bit-identity oracle and
    the order every pre-PR-15 run used implicitly. ``overlap`` cuts
    that dependence with a double-buffered stage loop: bucket b+1's
    selection is issued with NO data dependency on bucket b's merge, so
    XLA's latency-hiding scheduler interleaves the selection compute
    with the in-flight ppermute rounds; merges still chain through a
    barrier (one collective in flight — the schedule's round structure
    is preserved). Both orders compute the SAME values through the SAME
    ops (barriers are identity), so results — params, residuals,
    telemetry counters — are bit-identical across serial/overlap for
    every codec and schedule; only the exposed wall-clock differs.
    ``auto`` prices both orders with the bucketing DP's span model and
    keeps the cheaper (ties to serial).

    ``momentum_correction`` (TPU extension, DGC arXiv:1712.01887 §3.1-3.2
    — not reference parity: the reference runs torch momentum-SGD on the
    sparse GLOBAL update) moves momentum BEFORE compression: each device
    keeps a local velocity ``u = momentum*u + grad``, the accumulated
    velocity ``v += u`` is what top-k selects from, transmitted
    coordinates are zeroed out of BOTH v and u (momentum factor masking),
    and the inner optimizer applies the reduced update without further
    momentum. This corrects the staleness that plain post-collective
    momentum suffers when a coordinate is transmitted only once every
    ~1/rho steps. Under gTop-k, masking follows the LOCAL selection: a
    locally-picked but globally-rejected coordinate keeps its VALUE in
    the residual (the error-feedback repair) but its velocity u stays
    masked. One could argue the velocity should survive too (nothing was
    transmitted), but the measured ablation says no — restoring u
    double-tracks the same mass (v += u while u compounds) and
    persistently-rejected coordinates blow up; see the
    ``restore_rejected_u_ablation`` entry of
    benchmarks/results/warmup_ab_cpu_mesh8.json and the NOTE at the
    repair site below. During a
    ``warmup_dense_steps`` phase the DENSE mean of u is communicated,
    which is algebraically identical to classic momentum-SGD on the mean
    gradient (mean is linear in u) — exactly the dense baseline at
    weight_decay=0; with weight decay the two differ in whether the
    wd·params term passes through the momentum trace (dense baseline)
    or is added un-momentum'd after the collective (correction).

    ``telemetry`` (obs subsystem) computes the on-device training-health
    counters (obs.counters.TELEMETRY_FIELDS: achieved wire density, top-k
    threshold tau, pre/post-compression gradient norms, error-feedback
    residual norm, modeled wire bytes) inside the jitted update and
    stores them in ``state.telemetry`` — a handful of scalar reductions,
    fused into ops the step already runs; under a bound mesh axis they
    are pmean'd so the stored values are replicated. Off by default: the
    disabled path traces bit-identically to before the flag existed.

    ``telemetry_layers`` (requires ``telemetry``) additionally resolves
    the counters PER LAYER (obs.counters.LAYER_FIELDS — achieved
    density, tau, pre/post grad norm, residual norm, mean residual age,
    mass-capture ratio m(k), arXiv:1911.08772) as f32[L] arrays under
    ``state.telemetry["layers"]``, where index i is leaf i of the grads
    pytree in jax.tree flatten order (obs.counters.layer_names gives the
    matching names). Layer identity is static trace-time structure, so
    the flat modes pay a few segment reductions over the [N] vector and
    the layerwise mode a small reduction per leaf; the
    ``state.telemetry["age"]`` buffer (steps since each coordinate last
    shipped, residual layout) updates from the globally-reduced update,
    which is replicated, so it needs no collective and is excluded from
    the pmean.

    ``telemetry_audit_interval`` > 0 (requires ``telemetry``) runs an
    exact-vs-production top-k recall audit every that-many optimizer
    steps: the exact top-k of the error-feedback accumulator (ops.topk's
    exact path as ground truth) is compared against the set the
    production kernel actually selected, and the recall fraction lands
    in ``state.telemetry["audit_recall"]`` (pmean of per-device
    recalls). Between audits the last audited value is carried; -1 means
    never audited (e.g. still in the dense warm-up phase). The exact
    top-k runs under a lax.cond, so non-audit steps pay nothing.
    """
    mode = compression
    if mode not in ALL_MODES:
        raise ValueError(f"unknown compression mode {mode!r}")
    hier = mode in HIER_MODES
    layerwise = mode in LAYERWISE_MODES
    if hier_ici_size < 1:
        raise ValueError(f"hier_ici_size must be >= 1, got {hier_ici_size}")
    if hier_ici_size > 1 and not hier:
        raise ValueError(
            f"hier_ici_size={hier_ici_size} only applies to hierarchical "
            f"modes {HIER_MODES}, not {mode!r}"
        )
    if warmup_dense_steps < 0:
        raise ValueError(
            f"warmup_dense_steps must be >= 0, got {warmup_dense_steps}"
        )
    if telemetry_audit_interval < 0:
        raise ValueError(
            f"telemetry_audit_interval must be >= 0, got "
            f"{telemetry_audit_interval}"
        )
    if (telemetry_layers or telemetry_audit_interval) and not telemetry:
        raise ValueError(
            "telemetry_layers / telemetry_audit_interval extend the "
            "telemetry counters; they require telemetry=True")
    audit = telemetry_audit_interval > 0
    if nesterov and not momentum:
        # torch.optim.SGD raises here too; silently running plain SGD while
        # the user believes Nesterov is active would be worse.
        raise ValueError("nesterov momentum requires momentum > 0")
    dense_mode = mode in DENSE_MODES
    correction = momentum_correction
    if correction:
        if dense_mode:
            raise ValueError(
                "momentum_correction only applies to sparse modes (the "
                "dense path IS classic momentum-SGD already)")
        if not momentum:
            raise ValueError("momentum_correction requires momentum > 0")
        if nesterov:
            raise ValueError(
                "momentum_correction defines its own velocity recursion; "
                "nesterov is not expressible in it")
    if _restore_rejected_u and not correction:
        raise ValueError("_restore_rejected_u is a momentum_correction "
                         "ablation knob; it needs momentum_correction=True")
    if correction and layerwise:
        import warnings

        # Measured, twice: the combination underperforms BOTH parents at
        # the 200-step A/B (val_top1 0.250 vs 0.734 correction-alone /
        # 0.281 layerwise-alone), and the round-3 masking ablations show
        # it is not a masking-semantics bug (restoring rejected-pick
        # velocities collapses it further, 0.094): per-leaf quota
        # selection neutralizes the velocity-informed global ranking that
        # makes correction work. Allowed (long-budget behavior unknown)
        # but loudly non-default.
        warnings.warn(
            "gtopk_layerwise x momentum_correction measured WORSE than "
            "either alone (benchmarks/results/warmup_ab_cpu_mesh8.json: "
            "cold val_top1 0.250 vs 0.734/0.281; masking ablations rule "
            "out a semantics fix) — prefer one or the other",
            stacklevel=2)
    compressor = get_compressor(mode, density=density, method=topk_method)
    # Validate the codec spec at build time (bad --wire-codec fails here,
    # not inside the jitted step); the instance is reused every step.
    codec = get_codec(wire_codec)
    # Same build-time discipline for the wire plan: a pin that does not
    # realize this mode fails here. The plan itself is resolved at TRACE
    # time (resolve_plan below), when the mesh axis size is known — the
    # planner memoizes per shape, so retracing costs a dict lookup. The
    # codec's canonical name keys the planner cache (wire_codec may be a
    # WireCodec instance).
    comm_plan = validate_pin(comm_plan, mode, ici_size=hier_ici_size)
    codec_spec = getattr(codec, "name", "fp32")
    # Same build-time discipline for --buckets: the spec parses (or
    # fails) here; the partition itself is resolved at trace time, when
    # the leaf sizes are known (plan_buckets below, memoized in the
    # bucketing DP). Bucketing is a layerwise merge granularity — every
    # other mode has exactly one wire set per step by construction.
    bucket_spec = parse_buckets(buckets)
    if bucket_spec != "concat" and not layerwise:
        raise ValueError(
            f"--buckets {buckets!r} only applies to the layerwise mode "
            f"{LAYERWISE_MODES}; {mode!r} has a single wire set per step "
            "already (use --buckets concat)")
    # Same build-time discipline for --pipeline: the spec parses (or
    # fails) here; 'auto' resolves at trace time inside plan_buckets,
    # where the partition and span model live. Overlap needs a bucket
    # axis to pipeline over — a concat wire has ONE select and ONE
    # merge per step, nothing to double-buffer ('auto' degrades to
    # serial there instead of failing, because there is no decision to
    # make).
    pipeline_spec = parse_pipeline(pipeline)
    if pipeline_spec == "overlap" and bucket_spec == "concat":
        raise ValueError(
            f"--pipeline overlap requires a bucketed layerwise wire "
            f"(--buckets leaf|auto|<int B>); --buckets concat has a "
            "single select/merge pair per step, so there are no stages "
            "to overlap (use --pipeline serial or auto)")
    inner = optax.chain(
        optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
        # With momentum correction the velocity lives BEFORE the collective
        # (in state.residual["u"]); the inner optimizer must not apply
        # momentum a second time.
        optax.sgd(learning_rate,
                  momentum=None if correction else (momentum or None),
                  nesterov=nesterov),
    )

    def bound_axis_size() -> int:
        """Size of the mesh axis `update` is actually tracing under (static).
        1 when axis_name is unset or unbound (single-worker path)."""
        if axis_name is None:
            return 1
        try:
            p = lax.axis_size(axis_name)
        except NameError:  # not inside shard_map over axis_name
            if axis_size is not None and axis_size > 1:
                # The caller explicitly expects a multi-device run; falling
                # back to p=1 would silently skip every collective and let
                # replicas drift. Fail loudly instead.
                raise ValueError(
                    f"axis_size={axis_size} was given but mesh axis "
                    f"{axis_name!r} is not bound — is update() running "
                    "inside jax.shard_map over that axis?"
                ) from None
            return 1
        if axis_size is not None and axis_size != p:
            raise ValueError(
                f"axis_size={axis_size} disagrees with mesh axis "
                f"{axis_name!r} of size {p}"
            )
        return p

    def _init_telemetry(params):
        tel = obs_counters.zero_telemetry()
        if telemetry_layers:
            tel.update(obs_counters.zero_layer_telemetry(
                obs_counters.layer_sizes(params), per_leaf_age=layerwise))
        if audit:
            tel["audit_recall"] = jnp.float32(-1.0)
        return tel

    def init_fn(params) -> GTopKSGDState:
        if layerwise:
            residual = tuple(
                jnp.zeros((int(leaf.size),), jnp.float32)
                for leaf in jax.tree.leaves(params)
            )
        else:
            flat, _ = ravel_pytree(params)
            residual = compressor.init_residual(flat.shape[0])
        if correction:
            # v: the accumulated-velocity buffer selection reads (plays the
            # error-feedback residual's role); u: the local momentum buffer.
            residual = {"v": residual,
                        "u": jax.tree.map(jnp.zeros_like, residual)}
        return GTopKSGDState(
            count=jnp.zeros((), jnp.int32),
            residual=residual,
            inner=inner.init(params),
            telemetry=_init_telemetry(params) if telemetry else (),
        )

    def _finish_telemetry(tel, p):
        """pmean the per-device scalars (and [L] layer stats) when a mesh
        axis is bound so the stored telemetry is replicated (out_specs
        P() in the trainer); per-device quantities (residual norm, sent
        count) become axis means — the aggregate a dashboard wants
        anyway. The "age" buffer is EXCLUDED: it is replicated by
        construction (derived from the globally-reduced update), and
        pmean'ing it would spend an O(N) collective on a no-op."""
        if p > 1:
            tel = {
                key: (v if key == "age" else jax.tree.map(
                    lambda x: lax.pmean(x, axis_name), v))
                for key, v in tel.items()
            }
        return tel

    def layerwise_update(grads, state: GTopKSGDState, params=None):
        """Per-layer select/feedback; global reduce on the concatenated set.

        Mirrors the flat update_fn pipeline stage for stage; differs only
        in WHERE selection and error feedback live (one buffer per layer,
        never one [N] vector). Leaf order is jax.tree.flatten order of the
        grads pytree, which init_fn used for the residual, so the two
        always align."""
        leaves, treedef = jax.tree.flatten(grads)
        sizes = [int(leaf.size) for leaf in leaves]
        ks = [k_for_density(s, density) for s in sizes]
        offsets, off = [], 0
        for s in sizes:
            offsets.append(off)
            off += s
        n = off
        kk_total = sum(ks)
        flats = [leaf.reshape(-1) for leaf in leaves]
        if clip_grad_norm is not None:
            # Same clip-BEFORE-compress order as the flat path; the global
            # norm is a sum of per-leaf sums — no concatenation needed.
            gnorm = jnp.sqrt(sum(jnp.sum(f * f) for f in flats))
            scale = jnp.minimum(1.0, clip_grad_norm / (gnorm + 1e-6))
            flats = [f * scale for f in flats]
        p = bound_axis_size()
        # Bucket partition for this (leaf_sizes, density, p, codec) —
        # the alpha-beta DP of parallel.bucketing; None under the
        # historical 'concat' wire. Resolved host-side at trace time
        # (the DP table is memoized), so boundaries are static
        # structure from here on, like offsets and ks.
        bplan = (plan_buckets(tuple(sizes), density, buckets=bucket_spec,
                              p=p, codec=codec_spec, mode=mode,
                              pipeline=pipeline_spec)
                 if bucket_spec != "concat" else None)
        wire_k_total = bplan.k_total if bplan is not None else kk_total
        # Resolved execution order for the bucketed stage loop below —
        # plan_buckets decided an 'auto' spec against the span model;
        # the concat wire has no stage loop and is serial by
        # construction.
        pipe = bplan.pipeline if bplan is not None else "serial"
        # Wire plan for this (mode, mesh, n, k, codec) — chosen by the
        # topology planner unless pinned; None at p=1 (no wire).
        # Bucketed runs key and score the candidates on the (n_b, k_b)
        # pairs — B merges each, not one concatenated merge.
        plan = (resolve_plan(mode, p, n, wire_k_total, codec_spec, 1,
                             comm_plan, None, buckets_key(bucket_spec),
                             bplan.pairs() if bplan is not None else None,
                             pipe)
                if p > 1 else None)

        if correction:
            res_in = state.residual["v"]
            us = tuple(momentum * u + f
                       for u, f in zip(state.residual["u"], flats))
            srcs = list(us)
        else:
            res_in = state.residual
            us = ()
            srcs = flats

        def _audit_recall(accs, hits_fn):
            """Sampled exact-vs-production recall: exact top-kk_total of
            the concatenated accumulator as ground truth, compared
            against the production selection via ``hits_fn(exact_idx) ->
            bool[k]`` membership. The concatenation and exact top-k only
            exist inside the cond's taken branch — non-audit steps pay
            nothing."""
            def _do():
                ev, ei = topk_abs(jnp.concatenate(accs), kk_total)
                return obs_counters.topk_recall(hits_fn(ei), ev)

            return lax.cond(
                (state.count % telemetry_audit_interval) == 0,
                _do, lambda: jnp.float32(-1.0))

        def sparse_branch(srcs, res_in, us):
            accs = [s + r for s, r in zip(srcs, res_in)]
            tel = ()
            if p == 1:
                # Threshold form of the per-leaf selection (see the flat
                # path's p=1 branch and compress_by_threshold's
                # docstring): each leaf's top-k_l becomes one small
                # reduction for tau_l plus elementwise masks — dropping
                # the per-leaf scatter+gather pairs, which at ~161
                # leaves were ~2x161 extra kernels on the step. The
                # per-leaf k = ceil(density * n_l) is exactly
                # compressor.k(n_l), so the shared helper applies
                # unchanged leaf by leaf.
                sel = [compressor.compress_by_threshold(
                           a, grad=s, residual=r)
                       for a, s, r in zip(accs, srcs, res_in)]
                keeps = [keep for keep, _, _ in sel]
                new_res = [r for _, r, _ in sel]
                u_out = (tuple(jnp.where(m, 0.0, u)
                               for u, m in zip(us, keeps))
                         if correction else us)
                dense_fl = [a - r for a, r in zip(accs, new_res)]
                if telemetry:
                    # Whole-model tau from the per-leaf kept-taus the
                    # compressor already reduced (a leaf with a nonempty
                    # keep set always has tau > 0 — zeros never pass).
                    taus = jnp.stack([t for _, _, t in sel])
                    kept = taus > 0
                    tel = {
                        "tau": jnp.where(
                            jnp.any(kept),
                            jnp.min(jnp.where(kept, taus, jnp.inf)), 0.0),
                        "sent": sum(jnp.sum(m.astype(jnp.float32))
                                    for m in keeps),
                        "m_k": obs_counters.mass_ratio(accs, dense_fl),
                    }
                    if telemetry_layers:
                        tel["lsel"], _ = (
                            obs_counters.leafwise_selection_stats(
                                accs, dense_fl))
                    if audit:
                        tel["recall"] = _audit_recall(
                            accs,
                            lambda ei: jnp.take(
                                jnp.concatenate(keeps), ei, mode="clip"))
                    tel = (tel,)
                return (dense_fl, tuple(new_res), u_out) + tel
            sel = [select_topk(s, kl, topk_method, residual=r)
                   for s, r, kl in zip(srcs, res_in, ks)]
            idx_l = [i for _, i in sel]
            new_res = [a.at[i].set(0.0, mode="drop")
                       for a, i in zip(accs, idx_l)]
            # Momentum factor masking, per leaf, at the LOCAL selection
            # (see the measured-ablation note on the flat path).
            u_out = (tuple(u.at[i].set(0.0, mode="drop")
                           for u, i in zip(us, idx_l))
                     if correction else us)
            vals = jnp.concatenate([v for v, _ in sel])
            idx = jnp.concatenate([
                (i + o).astype(jnp.int32)
                for i, o in zip(idx_l, offsets)
            ])
            if codec.lossy:
                # Wire-error fold, layerwise twin: requantize the
                # concatenated set, ship vq, and scatter the error back
                # into each leaf's residual with the same static
                # [pos:pos+k_l] slices the repair uses — the error is in
                # concatenation order because roundtrip_aligned returns
                # original slot order.
                vq = roundtrip_aligned(codec, vals, idx, n=n)
                err = vals - vq
                folded, pos = [], 0
                for r, i, kl in zip(new_res, idx_l, ks):
                    folded.append(
                        r.at[i].add(err[pos:pos + kl], mode="drop"))
                    pos += kl
                new_res = folded
                vals = vq
            gvals, gidx, _ = sparse_allreduce(
                mode, vals, idx, k=kk_total, n=n,
                axis_name=axis_name, axis_size=p, codec=codec,
                plan=plan,
            )
            # Error-feedback repair, split back per leaf: put_back's layout
            # IS the concatenation order, so static [pos:pos+k_l] slices
            # address each leaf's candidates.
            rejected = ~membership_mask(idx, gidx)
            put_back = jnp.where(rejected, vals, 0.0)
            repaired, pos = [], 0
            for r, i, kl in zip(new_res, idx_l, ks):
                repaired.append(
                    r.at[i].add(put_back[pos:pos + kl], mode="drop"))
                pos += kl
            # u stays masked at the full LOCAL selection even for
            # globally-rejected picks — see the measured-ablation note on
            # the flat path (restoring u alongside the repaired value
            # double-tracks the same mass and diverges). Layerwise raises
            # the stakes: per-leaf ceil rounding makes tiny leaves pick
            # (and usually get globally rejected) EVERY step, so local
            # masking zeroes their velocity every step — the ablation
            # knob below measures the alternative for exactly this case
            # (warmup_ab layerwise arms).
            if correction and _restore_rejected_u:
                restored, pos = [], 0
                for u_masked, u_orig, i, kl in zip(u_out, us, idx_l, ks):
                    restored.append(u_masked.at[i].add(
                        jnp.where(rejected[pos:pos + kl], u_orig[i], 0.0),
                        mode="drop"))
                    pos += kl
                u_out = tuple(restored)
            dense = scatter_add_dense(n, gidx, gvals) / p
            dense_fl = [dense[o:o + s] for o, s in zip(offsets, sizes)]
            if telemetry:
                # Selection stats describe the LOCAL selection (what this
                # device put on the wire), matching sent_elems /
                # achieved_density semantics; the pmean in
                # _finish_telemetry turns them into axis means.
                tel = {
                    "tau": obs_counters.selected_tau(vals),
                    "sent": obs_counters.sent_count(vals),
                    "m_k": obs_counters.mass_ratio(accs, vals),
                }
                if telemetry_layers:
                    tel["lsel"], _ = (
                        obs_counters.leafwise_sparse_selection_stats(
                            accs, [v for v, _ in sel]))
                if audit:
                    tel["recall"] = _audit_recall(
                        accs, lambda ei: membership_mask(ei, idx))
                tel = (tel,)
            return (dense_fl, tuple(repaired), u_out) + tel

        def bucketed_sparse_branch(srcs, res_in, us):
            """Per-BUCKET select/feedback/merge (parallel.bucketing).

            Same pipeline as sparse_branch run B times over bucket
            concatenations instead of once over leaves + one global
            merge: each bucket's (grad, residual) leaves concatenate
            into one flat operand, selection runs once per bucket with
            k_b = ceil(density * n_b), and the merge runs in the
            BUCKET-LOCAL index space (n = n_b) — B collectives per step,
            each a strictly smaller instance of the same codec-framed
            exchange. State stays per leaf: the residual, update, and
            (under correction) velocity scatter back through the static
            bucket offsets, so checkpoints and the warm-up dense branch
            see the identical per-leaf structure. At B=1 this IS the
            flat gtopk pipeline over the raveled model; at B=L it is
            per-leaf selection with per-leaf merges."""
            B = bplan.n_buckets
            ranges = [bplan.leaf_range(b) for b in range(B)]
            bks = list(bplan.ks)
            bns = list(bplan.sizes)

            def bconcat(parts):
                return [parts[lo] if hi - lo == 1
                        else jnp.concatenate(parts[lo:hi])
                        for lo, hi in ranges]

            def bsplit(bufs):
                """Per-bucket flats -> per-leaf flats (static slices)."""
                out = []
                for (lo, hi), buf in zip(ranges, bufs):
                    off = 0
                    for s in sizes[lo:hi]:
                        out.append(buf[off:off + s])
                        off += s
                return out

            bsrcs = bconcat(srcs)
            bres = bconcat(res_in)
            bus = bconcat(us) if correction else []
            accs = [s + r for s, r in zip(bsrcs, bres)]

            def _bucket_audit(hits_fn_per_bucket):
                """Exact-vs-production recall against the bucketed
                ground truth: per-bucket exact top-k_b (the contract the
                bucketed selection implements), hits concatenated into
                one recall fraction. Only exists inside the cond's
                taken branch."""
                def _do():
                    hits, evs = [], []
                    for b, (a, kb) in enumerate(zip(accs, bks)):
                        ev, ei = topk_abs(a, kb)
                        hits.append(hits_fn_per_bucket(b, ei))
                        evs.append(ev)
                    return obs_counters.topk_recall(
                        jnp.concatenate(hits), jnp.concatenate(evs))

                return lax.cond(
                    (state.count % telemetry_audit_interval) == 0,
                    _do, lambda: jnp.float32(-1.0))

            tel = ()
            if p == 1:
                # Threshold form per bucket (see sparse_branch's p=1
                # note): compressor.k(n_b) == k_b by construction, so
                # the shared helper applies bucket by bucket.
                sel = [compressor.compress_by_threshold(
                           a, grad=s, residual=r)
                       for a, s, r in zip(accs, bsrcs, bres)]
                keeps = [keep for keep, _, _ in sel]
                new_res = [r for _, r, _ in sel]
                u_out_b = ([jnp.where(m, 0.0, u)
                            for u, m in zip(bus, keeps)]
                           if correction else [])
                dense_b = [a - r for a, r in zip(accs, new_res)]
                if telemetry:
                    taus = jnp.stack([t for _, _, t in sel])
                    kept = taus > 0
                    tel = {
                        "tau": jnp.where(
                            jnp.any(kept),
                            jnp.min(jnp.where(kept, taus, jnp.inf)), 0.0),
                        "sent": sum(jnp.sum(m.astype(jnp.float32))
                                    for m in keeps),
                        "m_k": obs_counters.mass_ratio(accs, dense_b),
                    }
                    if telemetry_layers:
                        # Per-leaf stats from per-leaf slices of the
                        # bucket accumulator/selection — same values the
                        # unbucketed path reduces, just sliced out of
                        # the concatenations.
                        tel["lsel"], _ = (
                            obs_counters.leafwise_selection_stats(
                                bsplit(accs), bsplit(dense_b)))
                    if audit:
                        tel["recall"] = _bucket_audit(
                            lambda b, ei: jnp.take(
                                keeps[b], ei, mode="clip"))
                    tel = (tel,)
                dense_fl = bsplit(dense_b)
                res_fl = tuple(bsplit(new_res))
                u_out = tuple(bsplit(u_out_b)) if correction else us
                return (dense_fl, res_fl, u_out) + tel
            # --- Pipelined stage loop (--pipeline) ------------------
            # Each bucket is two stages: _select (the fused two-stage
            # local selection + error-feedback zero-out + codec error
            # fold) and _merge (the codec-framed collective in the
            # bucket-local index space + rejected-pick repair + dense
            # scatter). Both execution orders below run the SAME ops on
            # the SAME values — lax.optimization_barrier is the
            # identity — and differ ONLY in the dependence edges handed
            # to XLA's scheduler, so serial and overlap results
            # (params, residuals, telemetry) are bit-identical by
            # construction (test-pinned against the numpy oracle).

            def _select(b, gate=None):
                """Stage 1 of bucket b. ``gate`` (the serial pin) is a
                pytree this stage must not start before: threading the
                stage inputs through one optimization_barrier with it
                makes every op of this stage depend on the gated
                values."""
                s, r = bsrcs[b], bres[b]
                u = bus[b] if correction else None
                if gate is not None:
                    (s, r, u), _ = lax.optimization_barrier(
                        ((s, r, u), gate))
                v, i = select_topk(s, bks[b], topk_method, residual=r)
                new_r = (s + r).at[i].set(0.0, mode="drop")
                # Momentum factor masking at the LOCAL (bucket)
                # selection — same measured-ablation rationale as the
                # other paths.
                u_out = u.at[i].set(0.0, mode="drop") if correction else None
                if codec.lossy:
                    # Wire-error fold per bucket: requantize in the
                    # bucket-local index space (the smaller n_b is
                    # exactly what shrinks the codec's index words) and
                    # fold the error into the bucket residual before
                    # the merge.
                    vq = roundtrip_aligned(codec, v, i, n=bns[b])
                    new_r = new_r.at[i].add(v - vq, mode="drop")
                    v = vq
                return {"v": v, "i": i, "res": new_r, "u": u_out}

            def _merge(b, st):
                """Stage 2 of bucket b: the collective, the
                error-feedback repair of globally-rejected picks, and
                the averaged dense scatter."""
                gvals, gidx, _ = sparse_allreduce(
                    mode, st["v"], st["i"], k=bks[b], n=bns[b],
                    axis_name=axis_name, axis_size=p, codec=codec,
                    plan=plan,
                )
                rejected = ~membership_mask(st["i"], gidx)
                return dict(
                    st,
                    rej=rejected,
                    res=st["res"].at[st["i"]].add(
                        jnp.where(rejected, st["v"], 0.0), mode="drop"),
                    dense=scatter_add_dense(bns[b], gidx, gvals) / p)

            outs = []
            if pipe == "overlap" and B > 1:
                # Double-buffered stage loop: bucket b+1's selection is
                # issued with NO data dependency on bucket b's merge —
                # the selection compute runs while the ppermute rounds
                # are in flight — and the stage barrier makes merge b+1
                # wait on BOTH (one collective in flight, preserving
                # the schedule's round structure).
                nxt = _select(0)
                for b in range(B):
                    cur = nxt
                    nxt = _select(b + 1) if b + 1 < B else None
                    out = _merge(b, cur)
                    if nxt is not None:
                        out, nxt = lax.optimization_barrier((out, nxt))
                    outs.append(out)
            else:
                # Serial pin — the paper's strictly sequential
                # T_select + T_comm, and the bit-identity oracle: the
                # gate threads bucket b's merge outputs into bucket
                # b+1's selection inputs, so exactly one stage can be
                # in flight.
                gate = None
                for b in range(B):
                    out = _merge(b, _select(b, gate))
                    gate = (out["dense"], out["res"])
                    outs.append(out)
            idx_b = [o["i"] for o in outs]
            vals_b = [o["v"] for o in outs]
            rejected_b = [o["rej"] for o in outs]
            repaired = [o["res"] for o in outs]
            dense_bufs = [o["dense"] for o in outs]
            u_out_b = [o["u"] for o in outs] if correction else []
            if correction and _restore_rejected_u:
                # Ablation arm only — see the sparse_branch note.
                u_out_b = [
                    u_masked.at[i].add(
                        jnp.where(rej, u_orig[i], 0.0), mode="drop")
                    for u_masked, u_orig, i, rej in
                    zip(u_out_b, bus, idx_b, rejected_b)]
            dense_fl = bsplit(dense_bufs)
            if telemetry:
                tel = {
                    "tau": obs_counters.selected_tau(
                        jnp.concatenate(vals_b)),
                    "sent": sum(obs_counters.sent_count(v)
                                for v in vals_b),
                    "m_k": obs_counters.mass_ratio(accs, vals_b),
                }
                if telemetry_layers:
                    tel["lsel"], _ = (
                        obs_counters.bucketed_sparse_selection_stats(
                            accs, vals_b, idx_b, sizes,
                            bplan.boundaries))
                if audit:
                    tel["recall"] = _bucket_audit(
                        lambda b, ei: membership_mask(ei, idx_b[b]))
                tel = (tel,)
            res_fl = tuple(bsplit(repaired))
            u_out = tuple(bsplit(u_out_b)) if correction else us
            return (dense_fl, res_fl, u_out) + tel

        if bplan is not None:
            sparse_branch = bucketed_sparse_branch

        if warmup_dense_steps > 0:
            def dense_branch(srcs, res_in, us):
                if p > 1:
                    srcs = [lax.psum(s, axis_name) / p for s in srcs]
                # dense phase telemetry: no threshold, everything sent,
                # full mass capture, nothing to audit
                tel = ()
                if telemetry:
                    teld = {"tau": jnp.float32(0.0),
                            "sent": jnp.float32(n),
                            "m_k": jnp.float32(1.0)}
                    if telemetry_layers:
                        teld["lsel"], _ = (
                            obs_counters.dense_phase_selection_stats(
                                sizes))
                    if audit:
                        teld["recall"] = jnp.float32(-1.0)
                    tel = (teld,)
                return (srcs, res_in, us) + tel

            out = lax.cond(
                state.count < warmup_dense_steps,
                dense_branch, sparse_branch, srcs, res_in, us,
            )
        else:
            out = sparse_branch(srcs, res_in, us)
        if telemetry:
            dense_fl, residual, u_new, btel = out
        else:
            dense_fl, residual, u_new = out
        res_struct = residual
        if correction:
            residual = {"v": residual, "u": u_new}

        avg_grads = treedef.unflatten([
            d.reshape(leaf.shape) for d, leaf in zip(dense_fl, leaves)
        ])
        updates, inner_state = inner.update(avg_grads, state.inner, params)
        if telemetry:
            tel = obs_counters.make_telemetry(
                n=n, k=wire_k_total, p=p, mode=mode, codec=codec,
                schedule=plan.schedule if plan is not None else None,
                buckets=bplan.pairs() if bplan is not None else None,
                grad_norm_pre=obs_counters.tree_l2(flats),
                grad_norm_post=obs_counters.tree_l2(dense_fl),
                residual_norm=obs_counters.tree_l2(res_struct),
                tau=btel["tau"], sent_elems=btel["sent"],
                m_k=btel["m_k"],
            )
            if telemetry_layers:
                # Delivered = appeared in the globally-reduced update,
                # which is replicated — so the age buffer stays
                # replicated without a collective (see update_age).
                age = obs_counters.update_age(
                    state.telemetry["age"],
                    tuple(d != 0 for d in dense_fl))
                tel["layers"] = obs_counters.assemble_layer_telemetry(
                    sel_stats=btel["lsel"], sizes=sizes,
                    grad_norm_pre_l=obs_counters.leaf_l2(flats),
                    grad_norm_post_l=obs_counters.leaf_l2(dense_fl),
                    residual_norm_l=obs_counters.leaf_l2(res_struct),
                    age=age)
                tel["age"] = age
            if audit:
                # Carry the last audited value between audits; -1 means
                # never audited (dense warm-up included).
                tel["audit_recall"] = jnp.where(
                    btel["recall"] >= 0.0, btel["recall"],
                    state.telemetry["audit_recall"])
            tel = _finish_telemetry(tel, p)
        else:
            tel = state.telemetry
        new_state = GTopKSGDState(
            count=state.count + 1, residual=residual, inner=inner_state,
            telemetry=tel,
        )
        return updates, new_state

    def update_fn(grads, state: GTopKSGDState, params=None):
        if layerwise:
            return layerwise_update(grads, state, params)
        flat, unravel = ravel_pytree(grads)
        n = flat.shape[0]
        if telemetry_layers:
            # Static trace-time layer structure: ravel_pytree flattens in
            # jax.tree order, so the segment map addresses the same
            # leaves obs_counters.layer_names reports.
            l_sizes = obs_counters.layer_sizes(grads)
            l_seg = obs_counters.segment_ids(l_sizes)
            n_layers = len(l_sizes)
        if clip_grad_norm is not None:
            # Reference LSTM path: clip the raw local gradient BEFORE the
            # residual accumulate/compress (order matters for convergence).
            gnorm = jnp.sqrt(jnp.sum(flat * flat))
            scale = jnp.minimum(1.0, clip_grad_norm / (gnorm + 1e-6))
            flat = flat * scale

        p = bound_axis_size()
        if hier and p > 1:
            if p % hier_ici_size != 0:
                raise ValueError(
                    f"axis size {p} not divisible by "
                    f"hier_ici_size={hier_ici_size}"
                )
            # Level 1: dense sum within the ICI slice, BEFORE error feedback
            # — the slice acts as one logical worker from here on, and all
            # of its devices hold identical acc/top-k/residual.
            flat = ici_dense_psum(
                flat, axis_name=axis_name, axis_size=p,
                ici_size=hier_ici_size,
            )
        btel = None
        plan = None  # dense mode has no sparse wire to plan
        if dense_mode:
            reduced = lax.psum(flat, axis_name) if p > 1 else flat
            dense = reduced / p
            residual = state.residual
            res_struct = residual
            if telemetry:
                btel = {"tau": jnp.float32(0.0), "sent": jnp.float32(n),
                        "m_k": jnp.float32(1.0)}
                if telemetry_layers:
                    btel["lsel"], _ = (
                        obs_counters.dense_phase_selection_stats(l_sizes))
                if audit:
                    btel["recall"] = jnp.float32(-1.0)
        else:
            # Wire plan for this (mode, mesh, n, k, codec) — chosen by
            # the topology planner unless pinned; None at p=1 (no wire).
            plan = (resolve_plan(mode, p, n, compressor.k(n), codec_spec,
                                 hier_ici_size if hier else 1, comm_plan)
                    if p > 1 else None)
            if correction:
                # DGC velocity recursion on the LOCAL (or slice-summed, in
                # hier mode) gradient; selection reads v + u below.
                res_in = state.residual["v"]
                u = momentum * state.residual["u"] + flat
                src = u
            else:
                res_in = state.residual
                u = jnp.zeros((0,), flat.dtype)
                src = flat

            def sparse_branch(src, residual_in, u_in):
                acc = compressor.accumulate(src, residual_in)

                def _audit_recall(hits_fn):
                    """Exact-vs-production recall audit (see the
                    layerwise twin): exact top-k of acc as ground truth,
                    ``hits_fn(exact_idx) -> bool[k]`` membership in the
                    production selection; the exact top-k only exists
                    inside the cond's taken branch."""
                    def _do():
                        ev, ei = topk_abs(acc, compressor.k(n))
                        return obs_counters.topk_recall(hits_fn(ei), ev)

                    return lax.cond(
                        (state.count % telemetry_audit_interval) == 0,
                        _do, lambda: jnp.float32(-1.0))

                tel = ()
                if p == 1:
                    # No collective at p=1, so nothing ever needs the
                    # (vals, idx) wire format — select by THRESHOLD
                    # (compress_by_threshold): one top-k reduction for
                    # tau, then pure elementwise where-masks for the
                    # residual, the update, and the velocity. The
                    # index-set form dragged a scatter (zero the
                    # residual out) + gather (read the values) through
                    # the flat [N] vector, and that chain is what kept
                    # XLA from fusing selection into the backward
                    # epilogue (fused-step overhead was ~3x the isolated
                    # compress cost — fused_variants artifact; the
                    # before/after is in the round-3 bench artifact).
                    # Masking u at the same keep-mask is exact here:
                    # every local pick is delivered at p=1. The tau
                    # search reads (src, residual_in) unfused so the
                    # twostage/pallas kernels fold the error-feedback
                    # accumulate into their own selection pass — acc
                    # only feeds the elementwise masks, which XLA fuses.
                    keep, residual, tau_th = (
                        compressor.compress_by_threshold(
                            acc, grad=src, residual=residual_in))
                    dense = acc - residual
                    u_out = (jnp.where(keep, 0.0, u_in)
                             if correction else u_in)
                    if telemetry:
                        tel = {
                            "tau": tau_th,
                            "sent": jnp.sum(keep.astype(jnp.float32)),
                            "m_k": obs_counters.mass_ratio(acc, dense),
                        }
                        if telemetry_layers:
                            tel["lsel"], _ = (
                                obs_counters.selection_layer_stats(
                                    acc, dense, l_seg, n_layers))
                        if audit:
                            tel["recall"] = _audit_recall(
                                lambda ei: jnp.take(
                                    keep, ei, mode="clip"))
                        tel = (tel,)
                else:
                    vals, idx, residual = compressor.compress(
                        acc, grad=src, residual=residual_in)
                    if codec.lossy and mode != "topk":
                        # Fold the wire quantization error into the
                        # error-feedback residual and ship the
                        # requantized values: the residual repair below
                        # then restores vq + folded error = the exact
                        # original for rejected picks, and telemetry
                        # (tau/sent/mass) describes what actually went on
                        # the wire. (mode 'topk' allgathers the exact
                        # local picks — its codec path quantizes in
                        # topk_allgather and every pick is delivered, so
                        # there is nothing to repair and the small
                        # symmetric error is left to the next step's
                        # selection, like any dense rounding.)
                        vq = roundtrip_aligned(codec, vals, idx, n=n)
                        residual = compressor.fold_wire_error(
                            residual, idx, vals - vq)
                        vals = vq
                    if telemetry:
                        # Selection stats describe the LOCAL selection
                        # (what this device put on the wire); the pmean
                        # in _finish_telemetry turns them into axis
                        # means.
                        tel = {
                            "tau": obs_counters.selected_tau(vals),
                            "sent": obs_counters.sent_count(vals),
                            "m_k": obs_counters.mass_ratio(acc, vals),
                        }
                        if telemetry_layers:
                            tel["lsel"], _ = (
                                obs_counters.sparse_selection_layer_stats(
                                    acc, vals, idx, l_seg, n_layers))
                        if audit:
                            tel["recall"] = _audit_recall(
                                lambda ei: membership_mask(ei, idx))
                        tel = (tel,)
                    # Momentum factor masking: a DELIVERED coordinate's
                    # velocity restarts (its momentum was consumed);
                    # without this the same mass re-sends for ~1/momentum
                    # more steps. For the allgather union every local
                    # pick is delivered, so masking at the local
                    # selection is exact.
                    u_out = (u_in.at[idx].set(0.0, mode="drop")
                             if correction else u_in)
                    result, gidx, needs_repair = sparse_allreduce(
                        mode, vals, idx, k=compressor.k(n), n=n,
                        axis_name=axis_name, axis_size=p,
                        ici_size=hier_ici_size if hier else 1,
                        codec=codec, plan=plan,
                    )
                    if needs_repair:  # gtopk: sparse set + repair
                        residual = compressor.repair(
                            residual, vals, idx, gidx)
                        dense = scatter_add_dense(n, gidx, result) / p
                        # NOTE (measured design decision): under gTop-k a
                        # local pick can be globally REJECTED; one could
                        # argue its velocity should survive (nothing was
                        # transmitted). Measured ablation says NO: the
                        # repair above already preserves the rejected
                        # VALUE in v, so also keeping u double-tracks the
                        # same mass (v += u while u compounds) and
                        # persistently-rejected coordinates blow up —
                        # see restore_rejected_u_ablation in the
                        # warmup_ab_cpu_mesh8.json artifact. The local
                        # mask above is the stable generalization; the
                        # branch below exists ONLY to reproduce that
                        # ablation arm (_restore_rejected_u=True).
                        if correction and _restore_rejected_u:
                            rej = ~membership_mask(idx, gidx)
                            u_out = u_out.at[idx].add(
                                jnp.where(rej, u_in[idx], 0.0),
                                mode="drop")
                    else:  # allgather union: dense, every pick lands
                        dense = result / p
                return (dense, residual, u_out) + tel

            if warmup_dense_steps > 0:
                def dense_branch(src, residual_in, u_in):
                    reduced = lax.psum(src, axis_name) if p > 1 else src
                    # In hier mode the input is already the within-slice
                    # SUM (ici_dense_psum above), so a full-axis psum
                    # counts every original gradient hier_ici_size times —
                    # divide it back out or every warm-up step trains at
                    # an ici_size-inflated effective LR. With correction
                    # the mean of u IS classic momentum on the mean
                    # gradient (mean is linear in u), and u is NOT masked
                    # (nothing was transmitted sparsely).
                    scale = p * (hier_ici_size if (hier and p > 1) else 1)
                    # dense phase telemetry: no threshold, everything
                    # sent, full mass capture, nothing to audit
                    tel = ()
                    if telemetry:
                        teld = {"tau": jnp.float32(0.0),
                                "sent": jnp.float32(n),
                                "m_k": jnp.float32(1.0)}
                        if telemetry_layers:
                            teld["lsel"], _ = (
                                obs_counters.dense_phase_selection_stats(
                                    l_sizes))
                        if audit:
                            teld["recall"] = jnp.float32(-1.0)
                        tel = (teld,)
                    return (reduced / scale, residual_in, u_in) + tel

                out = lax.cond(
                    state.count < warmup_dense_steps,
                    dense_branch, sparse_branch, src, res_in, u,
                )
            else:
                out = sparse_branch(src, res_in, u)
            if telemetry:
                dense, residual, u_new, btel = out
            else:
                dense, residual, u_new = out
            res_struct = residual
            if correction:
                residual = {"v": residual, "u": u_new}

        avg_grads = unravel(dense)
        updates, inner_state = inner.update(avg_grads, state.inner, params)
        if telemetry:
            tel = obs_counters.make_telemetry(
                n=n, k=(n if dense_mode else compressor.k(n)), p=p,
                mode=mode, ici_size=hier_ici_size if hier else 1,
                codec=codec,
                schedule=plan.schedule if plan is not None else None,
                grad_norm_pre=obs_counters.tree_l2(flat),
                grad_norm_post=obs_counters.tree_l2(dense),
                residual_norm=obs_counters.tree_l2(res_struct),
                tau=btel["tau"], sent_elems=btel["sent"],
                m_k=btel["m_k"],
            )
            if telemetry_layers:
                # Delivered = appeared in the globally-reduced update,
                # which is replicated — so the age buffer stays
                # replicated without a collective (see update_age).
                age = obs_counters.update_age(
                    state.telemetry["age"], dense != 0)
                tel["layers"] = obs_counters.assemble_layer_telemetry(
                    sel_stats=btel["lsel"], sizes=l_sizes,
                    grad_norm_pre_l=obs_counters.seg_l2(
                        flat, l_seg, n_layers),
                    grad_norm_post_l=obs_counters.seg_l2(
                        dense, l_seg, n_layers),
                    residual_norm_l=(
                        jnp.zeros((n_layers,), jnp.float32)
                        if dense_mode else
                        obs_counters.seg_l2(res_struct, l_seg, n_layers)),
                    age=age, seg=l_seg)
                tel["age"] = age
            if audit:
                # Carry the last audited value between audits; -1 means
                # never audited (dense warm-up / dense mode included).
                tel["audit_recall"] = jnp.where(
                    btel["recall"] >= 0.0, btel["recall"],
                    state.telemetry["audit_recall"])
            tel = _finish_telemetry(tel, p)
        else:
            tel = state.telemetry
        new_state = GTopKSGDState(
            count=state.count + 1, residual=residual, inner=inner_state,
            telemetry=tel,
        )
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


def expand_residual_per_device(opt_state: GTopKSGDState, p: int, mesh):
    """Lift the freshly-initialized residual to the per-device [P, ...]
    convention used under shard_map (leading dim = 'dp'; strip with
    tree-mapped ``r[0]`` inside the block, restore with ``r[None]`` on the
    way out). Works leaf-wise, so it covers both the flat-[N] residual and
    the layerwise per-leaf pytree. The residual at init is zeros by
    construction, so each device's shard is created DIRECTLY in its
    P('dp') placement (make_array_from_callback) — a host-side broadcast
    would materialize the dense [P, N] array on one device first (1.6 GB
    for ResNet-50 x 16 workers), and a jitted zeros-with-out_shardings
    hits a jax sharding-override assertion when the persistent compilation
    cache is enabled. Shared by the trainer and the benchmark so their
    measured paths cannot drift.
    """
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec("dp"))

    def expand(res):
        res_shape = (p,) + res.shape

        def shard_zeros(index):
            shape = tuple(len(range(*s.indices(dim)))
                          for s, dim in zip(index, res_shape))
            return np.zeros(shape, res.dtype)

        return jax.make_array_from_callback(res_shape, sharding, shard_zeros)

    return opt_state._replace(
        residual=jax.tree.map(expand, opt_state.residual))


def wire_k(
    compression: Optional[str],
    density: float,
    n: int,
    leaf_sizes: Optional[tuple] = None,
) -> int:
    """Elements actually COMMUNICATED per device per step (n for dense).

    Flat sparse modes send k = ceil(rho*N). LAYERWISE_MODES send the
    concatenation of per-leaf selections, k_total = sum_l ceil(rho*n_l),
    which per-leaf ceil rounding can push SEVERALFOLD above ceil(rho*N)
    at low densities (ResNet-20 at rho=0.001 has dozens of
    sub-1000-element BN/bias leaves, each forced to k_l >= 1). Layerwise
    therefore REQUIRES ``leaf_sizes`` (e.g. ``[p.size for p in
    jax.tree.leaves(params)]``); calling without them raises instead of
    silently underestimating. Single source of the wire-K definition —
    the benchmark comm model and effective_density both derive from it."""
    if compression in DENSE_MODES:
        return n
    if compression in LAYERWISE_MODES:
        if not leaf_sizes:
            raise ValueError(
                "wire_k/effective_density for layerwise modes needs "
                "leaf_sizes: per-leaf ceil rounding makes the communicated "
                "set sum(ceil(rho*n_l)), not ceil(rho*N)")
        return sum(k_for_density(int(s), density) for s in leaf_sizes)
    return k_for_density(n, density)


def effective_density(
    compression: Optional[str],
    density: float,
    leaf_sizes: Optional[tuple] = None,
) -> float:
    """Density actually communicated (1.0 for the dense baseline) —
    ``wire_k / N``; see wire_k for the layerwise leaf_sizes requirement."""
    if compression in DENSE_MODES:
        return 1.0
    if compression in LAYERWISE_MODES:
        n = sum(int(s) for s in leaf_sizes) if leaf_sizes else 0
        return wire_k(compression, density, n, leaf_sizes) / n
    return density
