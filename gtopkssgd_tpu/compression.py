"""Gradient compression with error feedback — pure-functional, jit-resident.

Reference parity (compression.py::TopKCompressor in hclhkbu/gtopkssgd,
SURVEY.md C4): per-step the reference keeps a class-attribute `residuals`
dict, computes `acc = grad + residual`, selects `torch.topk(|acc|, k)`,
zeroes the selected entries out of the residual, and after the allreduce
calls `add_residuals(...)` to return locally-selected-but-globally-rejected
values to the residual (the gTop-k error-feedback repair).

TPU-native redesign: the residual is an explicit flat f32[N] array owned by
the optimizer state (one pytree — so Orbax checkpoints it, fixing the
reference's silent residual reset on resume), and every operation below is a
pure function traced once under `jit`. There is no mutation, no dict keyed by
layer name (the reference flattens all layer grads into one vector per step
anyway — we do the same with `ravel_pytree`), and no host round-trip.

The three-stage protocol used by the distributed optimizer:

    acc             = grad + residual                     (accumulate)
    vals, idx, res' = compress(acc)                       (select + zero-out)
    gvals, gidx     = <sparse allreduce over the dp axis> (parallel/)
    res''           = repair(res', vals, idx, gidx)       (error-feedback fix)
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from gtopkssgd_tpu import modes
from gtopkssgd_tpu.ops import (
    k_for_density,
    membership_mask,
    select_tau,
    select_topk,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class TopKCompressor:
    """Magnitude top-k with error feedback. `density` = k / N (reference flag
    `--density`, rho, typically 1e-3). `method` picks the selection kernel
    (see ops.topk.select_topk): auto | exact | blockwise | approx | pallas
    | twostage (fused two-stage bucket select, arXiv:2506.04165) |
    simrecall (the CPU-runnable pessimistic approx stand-in)."""

    density: float
    method: str = "auto"

    def k(self, n: int) -> int:
        return k_for_density(n, self.density)

    def init_residual(self, n: int, dtype=jnp.float32) -> Array:
        return jnp.zeros((n,), dtype)

    def accumulate(self, grad_flat: Array, residual: Array) -> Array:
        """acc = grad + residual (the error-feedback accumulation)."""
        return grad_flat + residual

    def compress(
        self,
        acc: Array,
        *,
        grad: Optional[Array] = None,
        residual: Optional[Array] = None,
    ) -> Tuple[Array, Array, Array]:
        """Select top-k of |acc|; residual keeps everything not selected.

        Returns (vals f32[k], idx i32[k], residual f32[N]).

        When the caller passes the unfused operands (`grad`, `residual`
        with acc == grad + residual), the selection reads them directly —
        the `twostage` kernel folds the error-feedback accumulate into
        its own stage-1 HBM pass instead of consuming a materialized
        accumulator (the other methods fold in XLA; same values either
        way). The returned residual is still acc with the selected
        entries zeroed.
        """
        n = acc.shape[0]
        if grad is not None:
            vals, idx = select_topk(grad, self.k(n), self.method,
                                    residual=residual)
        else:
            vals, idx = select_topk(acc, self.k(n), self.method)
        residual_out = acc.at[idx].set(0.0, mode="drop")
        return vals, idx, residual_out

    def compress_by_threshold(
        self,
        acc: Array,
        *,
        grad: Optional[Array] = None,
        residual: Optional[Array] = None,
    ) -> Tuple[Array, Array, Array]:
        """Mask-form selection for paths that need no wire format.

        Returns (keep bool[N], residual f32[N], kept_tau f32[]) with
        ``keep = |acc| >= tau`` where tau is the k-th largest magnitude
        (as reported by the configured selection kernel),
        ``residual = where(keep, 0, acc)``, and ``kept_tau`` the smallest
        magnitude actually KEPT (0 when the keep set is empty) — the obs
        ``keep_tau`` convention, reported from here so telemetry callers
        do not re-reduce the same mask.

        Semantically this is the same partition as ``compress`` —
        selected entries leave the residual, everything else stays — but
        expressed without index sets: no scatter to zero the residual, no
        gather to read the values. At p=1 (or any point where the
        selected set is applied locally rather than sent), index sets
        buy nothing, and the scatter/gather chain they drag in is what
        blocks XLA from fusing the selection into the surrounding
        elementwise pipeline (measured: the fused-step gtopk-over-dense
        overhead was ~3x the isolated compress cost before this path —
        see benchmarks/results/fused_variants_TPU_v5_lite.json and the
        p1_threshold entry of the round-3 bench artifact).

        Set-membership caveats vs ``compress``, both convergence-neutral
        under error feedback (the keep/residual partition stays exact by
        construction): magnitude ties at tau all pass (count can exceed
        k), and with the approx kernel tau is the smallest magnitude the
        kernel FOUND, so elements the kernel missed but whose magnitude
        still clears tau are selected here even though compress would
        have dropped them (a strict superset — threshold recall is >=
        the kernel's). When tau == 0 (fewer than k nonzeros in acc, or a
        kernel padding its value slots with 0.0), zeros are masked OUT of
        the keep set rather than selected: |x| >= 0 is vacuously true,
        and "select all" would e.g. zero an entire velocity buffer under
        momentum correction instead of touching <=k coordinates like the
        index form does.

        tau comes from the tau-only API (ops.select_tau) — no k-sized
        (vals, idx) set is materialized and no gather runs just to read
        one scalar. When the caller passes the unfused operands (`grad`,
        `residual` with acc == grad + residual), the tau search reads
        them directly, fusing the error-feedback accumulate into the
        selection pass for the twostage/pallas kernels."""
        n = acc.shape[0]
        if grad is not None:
            tau = select_tau(grad, self.k(n), self.method,
                             residual=residual)
        else:
            tau = select_tau(acc, self.k(n), self.method)
        keep = (jnp.abs(acc) >= tau) & (jnp.abs(acc) > 0.0)
        kept_tau = jnp.min(jnp.where(keep, jnp.abs(acc), jnp.inf))
        kept_tau = jnp.where(
            jnp.isfinite(kept_tau), kept_tau, 0.0).astype(jnp.float32)
        return keep, jnp.where(keep, 0.0, acc), kept_tau

    def repair(
        self,
        residual: Array,
        local_vals: Array,
        local_idx: Array,
        global_idx: Array,
    ) -> Array:
        """Error-feedback repair: local selections that did NOT survive the
        global top-k go back into the residual (reference `add_residuals`).
        Without this step their gradient mass would be lost forever and
        convergence degrades — SURVEY.md §7 hard-part #4.

        Known semantic subtlety (inherent to gTop-k, reference included):
        membership is judged against the FINAL global set, so a contribution
        that was dropped mid-tree (its index lost an intermediate top-k) but
        whose index later survived via other devices' mass is counted as
        delivered even though it wasn't — that mass leaks (~0.1-1% of
        communicated mass per step, measured on random gradients). This is
        exactly the gTop-k vs exact-top-k approximation analyzed in
        arXiv:1911.08772; error feedback still bounds the error because the
        leak only affects co-selected coordinates."""
        rejected = ~membership_mask(local_idx, global_idx)
        put_back = jnp.where(rejected, local_vals, 0.0)
        return residual.at[local_idx].add(put_back, mode="drop")

    def fold_wire_error(
        self,
        residual: Array,
        local_idx: Array,
        wire_err: Array,
    ) -> Array:
        """Fold wire-codec quantization error into the residual.

        ``wire_err = vals - dequant(quant(vals))`` per selected slot
        (parallel.codec.roundtrip_aligned keeps original slot order, so
        it lines up with ``local_idx``). Called BEFORE the collective:
        the shipped values become the requantized ones, the error stays
        local, and the ``repair`` above — which restores the SHIPPED
        value for rejected picks — then composes exactly: requantized
        value + folded error = the original selection. Sentinel slots
        (idx == n) carry zero error and drop out of the scatter."""
        return residual.at[local_idx].add(wire_err, mode="drop")


@dataclasses.dataclass(frozen=True)
class NoneCompressor:
    """Dense passthrough (reference `NoneCompressor`): no selection, no
    residual. Used by the dense-psum baseline path."""

    density: float = 1.0
    method: str = "none"

    def k(self, n: int) -> int:
        return n

    def init_residual(self, n: int, dtype=jnp.float32) -> Array:
        return jnp.zeros((0,), dtype)

    def accumulate(self, grad_flat: Array, residual: Array) -> Array:
        return grad_flat

    def compress(self, acc: Array, *, grad: Optional[Array] = None,
                 residual: Optional[Array] = None
                 ) -> Tuple[Array, Array, Array]:
        n = acc.shape[0]
        idx = jnp.arange(n, dtype=jnp.int32)
        return acc, idx, jnp.zeros((0,), acc.dtype)

    def repair(self, residual, local_vals, local_idx, global_idx):
        return residual


# Name -> class registry, mirroring the reference's module-level
# `compressors` dict ({'topk': TopKCompressor, 'none'/None: NoneCompressor}).
# Keys are derived from the package-wide mode vocabulary (modes.py) so the
# registry can never drift from what the optimizer/collectives accept.
compressors = {
    **{m: NoneCompressor for m in modes.DENSE_MODES},
    **{m: TopKCompressor for m in modes.SPARSE_MODES},
}


def get_compressor(
    name: Optional[str], density: float = 0.001, method: str = "auto"
):
    """Build a configured compressor instance from the `compressors` registry."""
    try:
        cls = compressors[name]
    except KeyError:
        raise ValueError(f"unknown compressor {name!r}") from None
    if cls is NoneCompressor:
        return NoneCompressor()
    return cls(density=density, method=method)
