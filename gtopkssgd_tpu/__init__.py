"""gtopkssgd_tpu — a TPU-native framework for gTop-k sparsified synchronous SGD.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of the reference
repo hclhkbu/gtopkssgd (gTop-k S-SGD, Shi et al., ICDCS 2019, arXiv:1901.04359):
synchronous data-parallel SGD where each step every replica

  1. accumulates its dense gradient into a local error-feedback residual,
  2. selects the local top-k elements by magnitude (k = density * num_params),
  3. runs a tree-structured sparse allreduce ("gTop-k") producing one global
     set of k (index, value) pairs in O(k log P) communication,
  4. applies only those k values, returning globally-rejected values to the
     residual.

Instead of the reference's PyTorch + mpi4py + CUDA stack this package is
TPU-first: pure-functional train steps under `jax.jit`, SPMD over a
`jax.sharding.Mesh` data-parallel axis, `lax.ppermute` hypercube exchanges
riding ICI instead of MPI Send/Recv, `lax.top_k`/Pallas for k-selection
instead of `torch.topk`, and the error-feedback residual carried as
optimizer state inside one pytree (so checkpointing captures it — unlike
the reference, which silently dropped residuals on resume).

Layer map (mirrors SURVEY.md; reference layer in parens):

  gtopkssgd_tpu.trainer        -- single-replica trainer   (L3  dl_trainer.py)
  gtopkssgd_tpu.dist_trainer   -- distributed driver       (L4  dist_trainer.py)
  gtopkssgd_tpu.optimizer      -- distributed optimizer    (L2  optimizer wrapper)
  gtopkssgd_tpu.compression    -- top-k + error feedback   (L2  compression.py)
  gtopkssgd_tpu.parallel       -- sparse collectives       (L1  allreducer.py)
  gtopkssgd_tpu.models         -- model zoo                (C7  vgg/resnet/lstm*)
  gtopkssgd_tpu.data           -- data pipelines           (C8)
  gtopkssgd_tpu.ops            -- top-k / sparse kernels   (torch.topk CUDA)
  gtopkssgd_tpu.native         -- C++ host-side runtime    (torchvision/OpenMPI native code)
  gtopkssgd_tpu.utils          -- timers/logging/ckpt      (L0 settings.py, utils.py)
"""

__version__ = "0.1.0"

# ---------------------------------------------------------------- jax compat
# The codebase targets the public `jax.shard_map(..., check_vma=...)` and
# `lax.axis_size(...)` APIs. Older jax (< 0.5) only ships
# `jax.experimental.shard_map.shard_map` (same semantics under
# `check_rep`) and exposes the bound axis size as `core.axis_frame(name)`
# (an int; NameError when unbound — identical contract). Install
# forwarding aliases so every module (and the tests) can use the one
# spelling regardless of the installed jax. No-op on jax versions that
# already export them.
#
# The install is DEFERRED: importing this package must not itself import
# jax, because jax-free consumers exist — graftlint
# (``python -m gtopkssgd_tpu.analysis``) is pure stdlib-ast by contract
# and must run in seconds on a box whose accelerator tunnel is dead.
# A one-shot meta-path hook installs the aliases the moment anything
# first imports jax; if jax is already loaded, they install right away.


def _install_jax_compat() -> None:
    import jax
    from jax import lax

    try:
        jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as _experimental_sm

        def shard_map(f, /, *, mesh=None, in_specs=None, out_specs=None,
                      check_vma=None, **kwargs):
            if check_vma is not None:
                kwargs["check_rep"] = check_vma
            return _experimental_sm(f, mesh=mesh, in_specs=in_specs,
                                    out_specs=out_specs, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(lax, "axis_size"):
        from jax import core as _core

        def axis_size(axis_name):
            return _core.axis_frame(axis_name)

        lax.axis_size = axis_size


def _defer_jax_compat() -> None:
    import importlib.util
    import sys

    if "jax" in sys.modules:
        _install_jax_compat()
        return

    class _JaxCompatHook:
        """One-shot finder: resolves the real jax spec, wraps its
        loader so the compat aliases install immediately after jax's
        own __init__ runs, then retires itself."""

        _busy = False

        def find_spec(self, name, path=None, target=None):
            if name != "jax" or _JaxCompatHook._busy:
                return None
            _JaxCompatHook._busy = True
            try:
                spec = importlib.util.find_spec("jax")
            finally:
                _JaxCompatHook._busy = False
            try:
                sys.meta_path.remove(self)
            except ValueError:
                pass
            if spec is None or spec.loader is None:
                return spec
            orig_exec = spec.loader.exec_module

            def exec_module(module, _orig=orig_exec):
                _orig(module)
                _install_jax_compat()

            spec.loader.exec_module = exec_module
            return spec

    sys.meta_path.insert(0, _JaxCompatHook())


_defer_jax_compat()
