"""AN4 speech pipeline (reference C8: the deepspeech.pytorch-style audio
dataset + manifest files the reference's lstman4 workload consumes).

Real path: a manifest CSV of ``wav_path,transcript_path`` lines (the
deepspeech manifest format the reference used); wavs are read with
scipy.io.wavfile, converted to log-STFT spectrograms (20ms window, 10ms
hop, 161 bins at 16kHz), transcripts mapped over the 29-char vocabulary.

Synthetic fallback: random utterances whose spectrogram is correlated with
a random character sequence so CTC training has signal.

Every batch is padded to ONE fixed ``(max_frames, max_label_len)`` shape
(not the per-batch maximum): static shapes mean a single XLA compile, and
fixed shapes are what lets the trainer stack shards from P ranks /
nsteps_update micro-batches into one array. Utterances longer than
``max_frames`` (or transcripts longer than ``max_label_len``) are
truncated; the dataset counts these in ``truncated_count`` and logs a
warning the first time it happens on the real-data path.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Iterator, List

import numpy as np

from gtopkssgd_tpu.data.partition import DataPartitioner
from gtopkssgd_tpu.data.partition import signal_rng as _signal_rng
from gtopkssgd_tpu.data.partition import split_id as _split_id

# Blank at 0, then apostrophe, A-Z, space — the deepspeech English labels.
LABELS = "_'ABCDEFGHIJKLMNOPQRSTUVWXYZ "
CHAR_TO_ID = {c: i for i, c in enumerate(LABELS)}
N_BINS = 161
SYNTH_TRAIN, SYNTH_TEST = 256, 64


def text_to_ids(text: str) -> np.ndarray:
    return np.asarray(
        [CHAR_TO_ID[c] for c in text.upper() if c in CHAR_TO_ID], np.int32
    )


def wav_to_logspec(path: str) -> np.ndarray:
    import scipy.io.wavfile as wavfile
    import scipy.signal as sig

    sr, audio = wavfile.read(path)
    audio = audio.astype(np.float32) / 32768.0
    nperseg = int(0.02 * sr)
    noverlap = nperseg - int(0.01 * sr)
    _, _, spec = sig.stft(audio, sr, nperseg=nperseg, noverlap=noverlap,
                          nfft=320)
    return np.log1p(np.abs(spec.T)).astype(np.float32)  # [T, 161]


@functools.lru_cache(maxsize=4)
def _synth_utterances(split: str, seed: int, num_chars: int) -> List[Dict]:
    """Synthetic utterances whose spectrogram correlates with the transcript
    (per-char spectral signatures), cached so P rank objects share one list
    and seeded stably across processes (crc32, not hash())."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, _split_id(split)]))
    n = SYNTH_TRAIN if split == "train" else SYNTH_TEST
    # Split-INDEPENDENT per-char signatures: train and test must share the
    # char->spectrum mapping or held-out CER/WER on synthetic data is noise.
    signatures = _signal_rng(seed).standard_normal(
        (num_chars, N_BINS)).astype(np.float32)
    utts: List[Dict] = []
    for _ in range(n):
        L = int(rng.integers(4, 12))
        labels = rng.integers(1, num_chars, L).astype(np.int32)
        frames_per = int(rng.integers(6, 12))
        T = L * frames_per
        spec = 0.1 * rng.standard_normal((T, N_BINS)).astype(np.float32)
        for j, ch in enumerate(labels):
            spec[j * frames_per:(j + 1) * frames_per] += 0.5 * signatures[ch]
        utts.append({"spec": spec, "labels": labels})
    return utts


class AN4Dataset:
    num_chars = len(LABELS)

    def __init__(self, *, split="train", batch_size=8, rank=0, nworkers=1,
                 data_dir=None, seed=0, max_frames=400, max_label_len=64):
        self.split = split
        self.batch_size = batch_size
        self.max_frames = max_frames
        self.max_label_len = max_label_len
        manifest = os.path.join(
            data_dir or "", f"an4_{'train' if split == 'train' else 'val'}_manifest.csv"
        )
        self.synthetic = not os.path.isfile(manifest)
        if self.synthetic:
            self._utts = _synth_utterances(split, seed, self.num_chars)
            count = len(self._utts)
        else:
            # Manifest entries may be relative (the portable/committable
            # form) — resolve them against the manifest's own directory,
            # like deepspeech manifests in practice.
            mdir = os.path.dirname(os.path.abspath(manifest))
            self._manifest = [
                [p if os.path.isabs(p) else os.path.join(mdir, p)
                 for p in line.strip().split(",")]
                for line in open(manifest)
                if line.strip()
            ]
            self._utts = None
            count = len(self._manifest)
        self.partitioner = DataPartitioner(count, rank, nworkers, seed)
        if len(self.partitioner) < batch_size:
            raise ValueError(
                f"rank shard has {len(self.partitioner)} utterances < "
                f"batch_size {batch_size} — lower batch_size or nworkers"
            )
        self.truncated_count = 0
        self._warned_truncation = False

    def steps_per_epoch(self) -> int:
        return len(self.partitioner) // self.batch_size

    def _load(self, i: int) -> Dict:
        if self.synthetic:
            return self._utts[i]
        wav, txt = self._manifest[i][:2]
        return {
            "spec": wav_to_logspec(wav),
            "labels": text_to_ids(open(txt).read().strip()),
        }

    def epoch(self, epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        """Batches are padded to the FIXED (max_frames, max_label_len) shape,
        not the per-batch maximum: static shapes are what XLA wants (one
        compile), and fixed shapes are what lets the trainer stack shards
        from P ranks / nsteps_update micro-batches into one array."""
        idx = self.partitioner.indices(epoch)
        b = self.batch_size
        t_max, l_max = self.max_frames, self.max_label_len
        for lo in range(0, len(idx) - b + 1, b):
            utts = [self._load(i) for i in idx[lo:lo + b]]
            spec = np.zeros((b, t_max, N_BINS), np.float32)
            labels = np.zeros((b, l_max), np.int32)
            in_len = np.zeros((b,), np.int32)
            lab_len = np.zeros((b,), np.int32)
            for j, u in enumerate(utts):
                t = min(u["spec"].shape[0], t_max)
                l = min(len(u["labels"]), l_max)
                if u["spec"].shape[0] > t_max or len(u["labels"]) > l_max:
                    # Truncation silently drops CTC-visible audio/labels —
                    # keep a count and warn once so a real-data run with a
                    # too-small max_frames is noticed, not invisible.
                    self.truncated_count += 1
                    if not self._warned_truncation:
                        self._warned_truncation = True
                        import logging

                        logging.getLogger("gtopkssgd_tpu.data.an4").warning(
                            "utterance exceeds max_frames=%d/max_label_len=%d"
                            " and was truncated (counting further cases in "
                            "AN4Dataset.truncated_count)", t_max, l_max,
                        )
                spec[j, :t] = u["spec"][:t]
                labels[j, :l] = u["labels"][:l]
                in_len[j], lab_len[j] = t, l
            yield {
                "spectrogram": spec,
                "labels": labels,
                "input_lengths": in_len,
                "label_lengths": lab_len,
            }

    def __iter__(self):
        e = 0
        while True:
            yield from self.epoch(e)
            e += 1
