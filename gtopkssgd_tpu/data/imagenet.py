"""ImageNet pipeline (reference C8: ``ImageFolder`` over the standard
train/val directory layout inside dl_trainer.py).

Real path: ``data_dir/{train,val}/<wnid>/*.JPEG`` decoded with PIL,
random-resized-crop(224) + flip for train, resize(256)+center-crop(224) for
eval — the reference's torchvision recipe re-implemented host-side in
numpy/PIL.

Wire format is **uint8**: batches cross host->device as raw pixels (a
quarter of the float32 bytes — the TPU-first rule of minimizing H2D
transfer; on this environment's tunneled chip, measured ~45 MB/s, the f32
format alone cost ~800 ms per 64-image batch) and the ImageNet mean/std
normalization runs ON DEVICE inside the jitted step (trainer._loss_fn),
fused by XLA into the first conv. The reference normalized on the host
(torchvision ToTensor+Normalize) — same math, different placement.

Synthetic fallback generates class-conditional uint8 noise at full 224x224
so the ResNet-50/AlexNet benchmark path runs with the true compute shape
in a zero-egress environment.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Dict, Iterator, List, Tuple

import numpy as np

from gtopkssgd_tpu.data.partition import DataPartitioner
from gtopkssgd_tpu.data.partition import signal_rng as _signal_rng
from gtopkssgd_tpu.data.partition import split_id as _split_id

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)
SYNTH_TRAIN, SYNTH_TEST = 1024, 256


@functools.lru_cache(maxsize=4)
def _index_folder(root: str) -> Tuple[List[str], np.ndarray, List[str]]:
    classes = sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )
    paths, labels = [], []
    for ci, c in enumerate(classes):
        cdir = os.path.join(root, c)
        for f in sorted(os.listdir(cdir)):
            if f.lower().endswith((".jpeg", ".jpg", ".png")):
                paths.append(os.path.join(cdir, f))
                labels.append(ci)
    return paths, np.asarray(labels, np.int32), classes


def _decode_image(path: str, size: int, train: bool, rng) -> np.ndarray:
    """Decode + crop/flip one image, staying in uint8 end to end. Module
    level (not a method) so the worker pool can pickle it; ALL randomness
    comes from the passed rng so caller decides the determinism contract
    (sequential stream in-process, per-image seeded in the pool)."""
    from PIL import Image

    s = size
    with Image.open(path) as im:
        im = im.convert("RGB")
        if train:
            # random resized crop: area 8%-100%, aspect 3/4..4/3
            w, h = im.size
            for _ in range(10):
                area = w * h * rng.uniform(0.08, 1.0)
                ar = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
                cw, ch = int(round(np.sqrt(area * ar))), int(
                    round(np.sqrt(area / ar))
                )
                if cw <= w and ch <= h:
                    x0 = rng.integers(0, w - cw + 1)
                    y0 = rng.integers(0, h - ch + 1)
                    im = im.resize((s, s), box=(x0, y0, x0 + cw, y0 + ch))
                    break
            else:
                im = im.resize((s, s))
            arr = np.asarray(im, np.uint8)
            if rng.random() < 0.5:
                arr = arr[:, ::-1]
        else:
            w, h = im.size
            scale = 256 / min(w, h)
            im = im.resize((int(w * scale), int(h * scale)))
            w, h = im.size
            x0, y0 = (w - s) // 2, (h - s) // 2
            arr = np.asarray(im, np.uint8)[y0:y0 + s, x0:x0 + s]
    return arr


def _decode_seeded(args) -> np.ndarray:
    """Pool entry: per-image rng derived from (seed, split, epoch, index),
    so the augmentation stream is a pure function of those four — identical
    for ANY pool size (pinned by test) and across epochs-resume."""
    path, size, train, seed_key = args
    rng = np.random.default_rng(np.random.SeedSequence(seed_key))
    return _decode_image(path, size, train, rng)


# One decode pool per PROCESS, refcounted, shared by every dataset that
# asks for workers: a Trainer builds nworkers train shards + a val set,
# but _stack_shard_batches drains them strictly sequentially, so private
# per-dataset pools would fork (nworkers+1) x decode_workers processes of
# which at most one pool is ever busy. Pool size is fixed by the first
# acquirer (same cfg value for every dataset of a Trainer; per-image
# seeding makes results pool-size-independent anyway).
_pool_lock = threading.Lock()
_pool = None
_pool_refs = 0


def _acquire_decode_pool(n: int):
    global _pool, _pool_refs
    import multiprocessing as mp

    with _pool_lock:
        if _pool is None:
            _pool = mp.get_context("fork").Pool(n)
        _pool_refs += 1
        return _pool


def _release_decode_pool() -> None:
    global _pool, _pool_refs
    with _pool_lock:
        _pool_refs -= 1
        if _pool_refs <= 0 and _pool is not None:
            _pool.terminate()
            _pool.join()
            _pool = None
            _pool_refs = 0


class ImageNetDataset:
    example_shape = (224, 224, 3)

    def __init__(self, *, split="train", batch_size=32, rank=0, nworkers=1,
                 data_dir=None, seed=0, image_size=224, num_classes=1000,
                 decode_workers=0):
        self.split = split
        self.batch_size = batch_size
        self.image_size = image_size
        self.train = split == "train"
        subdir = "train" if self.train else "val"
        root = os.path.join(data_dir or "", subdir)
        self.synthetic = not os.path.isdir(root)
        self._seed = seed
        if self.synthetic:
            self.num_classes = num_classes
            n = SYNTH_TRAIN if self.train else SYNTH_TEST
            rng = np.random.default_rng(
                np.random.SeedSequence([seed, _split_id(split)])
            )
            self._labels = rng.integers(0, num_classes, n).astype(np.int32)
            # Split-INDEPENDENT class offsets: train and val must share the
            # class signal or held-out eval on synthetic data is chance.
            self._offsets = (
                _signal_rng(seed)
                .standard_normal((num_classes, 3)).astype(np.float32) * 0.25
            )
            self._paths = None
            count = n
        else:
            self._paths, self._labels, classes = _index_folder(root)
            self.num_classes = len(classes)
            count = len(self._paths)
        self.partitioner = DataPartitioner(count, rank, nworkers, seed)
        if len(self.partitioner) < batch_size:
            raise ValueError(
                f"rank shard has {len(self.partitioner)} samples < "
                f"batch_size {batch_size} — lower batch_size or nworkers"
            )
        # Decode worker pool (reference C8 parity: torchvision DataLoader
        # num_workers — the measured single-core decode rate, ~280 img/s,
        # is ~25x short of one v5e chip's bs=128 appetite, so the real-data
        # path MUST be able to spread decode across host cores:
        # benchmarks/results/input_path_1core_host.json). The 'fork'
        # context, deliberately (measured the alternatives the hard way):
        # 'spawn' AND 'forkserver' both re-import __main__, so any
        # unguarded user script crash-loops its own Pool (the standard
        # "safe importing of main module" contract), and both pay a full
        # jax re-import per worker. fork's own hazard — forking a parent
        # whose threads hold locks — is why the (shared) pool is acquired
        # EAGERLY here in __init__: dataset construction happens on the
        # main thread before the Prefetcher thread exists and before the
        # first XLA dispatch, so the fork window is clean; children run
        # ONLY numpy/PIL decode, never jax (same trade torch's DataLoader
        # defaults to on Linux). Both the pool and the sequential path use
        # per-image seeding (see _decode_seeded) so the stream is
        # identical for ANY pool size and reproducible mid-epoch.
        self.decode_workers = int(decode_workers) if not self.synthetic else 0
        self._pool = (_acquire_decode_pool(self.decode_workers)
                      if self.decode_workers > 0 else None)

    def close(self) -> None:
        """Drop this dataset's reference on the shared decode pool (the
        pool terminates when the last holder releases; its workers are
        daemonic, so process exit also reaps them). Safe to call
        repeatedly."""
        if self._pool is not None:
            self._pool = None
            _release_decode_pool()

    def steps_per_epoch(self) -> int:
        return len(self.partitioner) // self.batch_size

    # --- real-image decode path -------------------------------------------
    def _decode_at(self, i: int, epoch: int) -> np.ndarray:
        """Per-image seeded decode — same (seed, split, epoch, index)
        keying as the worker-pool path, so the sequential stream is a
        pure function of those values too (mid-epoch resume re-drains an
        epoch and must reproduce the crops exactly; a shared stateful rng
        would remember every earlier consumer)."""
        return _decode_seeded(
            (self._paths[i], self.image_size, self.train,
             (self._seed, _split_id(self.split), int(epoch), int(i))))

    def _synth_batch(self, sel: np.ndarray) -> np.ndarray:
        """Deterministic per-index generation: sample i is the same array on
        every pass and in every process, so eval metrics are comparable
        across epochs/runs without holding the whole set resident. uint8
        noise via integers() — an order of magnitude cheaper per sample
        than box-muller normals, which dominated host batch time."""
        s = self.image_size
        out = np.empty((len(sel), s, s, 3), np.int16)
        for j, i in enumerate(sel):
            rng = np.random.default_rng(
                np.random.SeedSequence([self._seed, _split_id(self.split), int(i)])
            )
            out[j] = rng.integers(64, 192, (s, s, 3), dtype=np.int16)
        # class-conditional channel shift so labels are learnable
        shift = (self._offsets[self._labels[sel]] * 255).astype(np.int16)
        out += shift[:, None, None, :]
        return np.clip(out, 0, 255).astype(np.uint8)

    def epoch(self, epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        idx = self.partitioner.indices(epoch)
        for lo in range(0, len(idx) - self.batch_size + 1, self.batch_size):
            sel = idx[lo:lo + self.batch_size]
            if self.synthetic:
                x = self._synth_batch(sel)
            elif self.decode_workers > 0:
                split_tag = _split_id(self.split)
                jobs = [
                    (self._paths[i], self.image_size, self.train,
                     (self._seed, split_tag, int(epoch), int(i)))
                    for i in sel
                ]
                x = np.stack(self._pool.map(_decode_seeded, jobs))
            else:
                x = np.stack([self._decode_at(i, epoch) for i in sel])
            yield {"image": x, "label": self._labels[sel]}

    def __iter__(self):
        e = 0
        while True:
            yield from self.epoch(e)
            e += 1
