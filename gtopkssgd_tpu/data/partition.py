"""Deterministic per-rank data sharding (reference C1: the
``Partition``/``DataPartitioner``-style rank sharding inside dl_trainer.py).

The reference partitions the training set into P disjoint slices, one per
MPI rank, shuffled with a shared seed so every rank computes the same
permutation without communicating. Same contract here; the per-epoch
reshuffle folds the epoch index into the seed (the reference reshuffled via
its sampler each epoch).
"""

from __future__ import annotations

import zlib

import numpy as np


def split_id(split: str) -> int:
    """Stable integer id for a split name, for RNG seeding. Python's
    ``hash()`` is randomized per process (PYTHONHASHSEED), which would give
    every host of a multi-host run a *different* synthetic dataset; crc32
    is stable across processes and runs."""
    return zlib.crc32(split.encode())


#: Stream key for the split-INDEPENDENT part of a synthetic dataset — the
#: class signal (CIFAR/ImageNet class-mean offsets, AN4 per-char spectral
#: signatures). Train and held-out splits must draw the signal from the
#: same stream or eval on synthetic data is structurally chance-level;
#: every generator goes through signal_rng() so none can drift back to a
#: per-split draw (tests/test_data.py pins the property).
SIGNAL_STREAM = 0xC1A55


def signal_rng(seed: int) -> np.random.Generator:
    """RNG for a synthetic dataset's split-independent class signal."""
    return np.random.default_rng(np.random.SeedSequence([seed, SIGNAL_STREAM]))


def partition_indices(
    n: int, rank: int, nworkers: int, seed: int = 0, epoch: int = 0
) -> np.ndarray:
    """This rank's disjoint slice of a shared permutation of range(n).

    All ranks calling with the same (n, nworkers, seed, epoch) derive the
    same permutation; slices are contiguous blocks of it, so they are
    disjoint and cover the set (the last worker absorbs the remainder).
    """
    if not 0 <= rank < nworkers:
        raise ValueError(f"rank {rank} out of range for {nworkers} workers")
    rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
    perm = rng.permutation(n)
    per = n // nworkers
    lo = rank * per
    hi = (rank + 1) * per if rank < nworkers - 1 else n
    return perm[lo:hi]


class DataPartitioner:
    """Object form used by the trainer: holds (n, rank, nworkers, seed) and
    hands out the per-epoch index slice."""

    def __init__(self, n: int, rank: int = 0, nworkers: int = 1, seed: int = 0):
        self.n = n
        self.rank = rank
        self.nworkers = nworkers
        self.seed = seed

    def indices(self, epoch: int = 0) -> np.ndarray:
        return partition_indices(
            self.n, self.rank, self.nworkers, self.seed, epoch
        )

    def __len__(self) -> int:
        per = self.n // self.nworkers
        return per if self.rank < self.nworkers - 1 else self.n - per * (
            self.nworkers - 1
        )
