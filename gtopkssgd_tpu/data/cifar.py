"""CIFAR-10 pipeline (reference C8: torchvision CIFAR-10 loaders with
random-crop + flip augmentation inside dl_trainer.py).

Reads the standard python-pickle batches (``cifar-10-batches-py``) from
``data_dir`` when present; otherwise generates a deterministic synthetic
stand-in with identical shapes/dtypes and a learnable class signal (class
mean offsets), so smoke training shows a falling loss without any download.

Augmentation matches the reference recipe: 4-pixel reflection pad + random
32x32 crop + horizontal flip, host-side (C++ when built, numpy fallback).

Wire format is **uint8**: batches cross host->device as raw NHWC pixels (a
quarter of the float32 bytes — minimize H2D, the TPU-first rule) and the
per-channel mean/std normalization runs ON DEVICE inside the jitted step
(trainer._loss_fn), where XLA fuses it into the first conv. The reference
normalized on the host (torchvision ToTensor+Normalize) — same math,
different placement.
"""

from __future__ import annotations

import functools
import os
import pickle
from typing import Dict, Iterator

import numpy as np

from gtopkssgd_tpu.data.partition import DataPartitioner
from gtopkssgd_tpu.data.partition import signal_rng as _signal_rng
from gtopkssgd_tpu.data.partition import split_id as _split_id

CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)
SYNTH_TRAIN, SYNTH_TEST = 2048, 512


@functools.lru_cache(maxsize=4)
def _load_real(data_dir: str, split: str):
    root = os.path.join(data_dir, "cifar-10-batches-py")
    files = (
        [f"data_batch_{i}" for i in range(1, 6)]
        if split == "train"
        else ["test_batch"]
    )
    images, labels = [], []
    for f in files:
        with open(os.path.join(root, f), "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        images.append(
            d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        )
        labels.append(np.asarray(d[b"labels"], np.int32))
    return (
        np.ascontiguousarray(np.concatenate(images)),  # u8 raw pixels
        np.concatenate(labels),
    )


@functools.lru_cache(maxsize=8)
def _synthetic(split: str, seed: int, hard: bool = False):
    """Class-conditional Gaussian images: separable, so loss curves mean
    something even without real data. Cached so the P per-rank dataset
    objects in one SPMD process share one array, not P copies.

    ``hard`` switches to the DISCRIMINATIVE variant (round-4 verdict
    missing #6: on the easy task every arm saturates val_top1=1.0 by
    step ~300 at the 1200-step budget, so accuracy parity between
    optimizer arms was unfalsifiable). Two changes: the class signal is
    a full 32x32x3 spatial pattern at low amplitude instead of a flat
    per-channel offset 6x stronger (augmentation crops/flips now
    actually perturb the signal, and the model must learn a pattern
    detector rather than an average-color probe), and 10% of TRAIN
    labels are resampled uniformly (test stays clean) so blind
    memorization costs clean-eval accuracy. Calibrated so the dense arm
    is still climbing at 1200 steps on the 2-way mesh rather than
    pinned at 1.0 — arms can separate.
    """
    n = SYNTH_TRAIN if split == "train" else SYNTH_TEST
    rng = np.random.default_rng(np.random.SeedSequence([seed, _split_id(split)]))
    labels = rng.integers(0, 10, n).astype(np.int32)
    # Class offsets come from the SPLIT-INDEPENDENT signal stream: train and
    # test must share the class signal or held-out eval on synthetic data is
    # structurally chance-level (the bug that made every synthetic val_top1
    # read ~0.1 before this).
    if hard:
        patterns = _signal_rng(seed).standard_normal(
            (10, 32, 32, 3)).astype(np.float32) * 0.07
        signal = patterns[labels]
    else:
        offsets = (_signal_rng(seed).standard_normal((10, 3))
                   .astype(np.float32) * 0.25)
        signal = offsets[labels][:, None, None, :]
    images = 0.5 + 0.15 * rng.standard_normal((n, 32, 32, 3)).astype(np.float32)
    images += signal
    images = np.clip(images, 0.0, 1.0)
    out_labels = labels
    if hard and split == "train":
        noisy = rng.random(n) < 0.10
        out_labels = np.where(
            noisy, rng.integers(0, 10, n).astype(np.int32), labels)
    # quantize once to the uint8 wire format (what real pickles hold)
    return (images * 255.0).round().astype(np.uint8), out_labels


class CIFAR10Dataset:
    num_classes = 10
    example_shape = (32, 32, 3)

    def __init__(self, *, split="train", batch_size=32, rank=0, nworkers=1,
                 data_dir=None, seed=0, augment=None, synth_hard=False):
        self.split = split
        self.batch_size = batch_size
        self.augment = (split == "train") if augment is None else augment
        root = data_dir or ""
        self.synthetic = not os.path.isdir(
            os.path.join(root, "cifar-10-batches-py")
        )
        if self.synthetic:
            self.images, self.labels = _synthetic(split, seed,
                                                  hard=synth_hard)
        else:
            self.images, self.labels = _load_real(root, split)
        self.partitioner = DataPartitioner(
            len(self.images), rank, nworkers, seed
        )
        if len(self.partitioner) < batch_size:
            raise ValueError(
                f"rank shard has {len(self.partitioner)} samples < "
                f"batch_size {batch_size} — lower batch_size or nworkers"
            )
        self._seed = seed
        self._rank = rank

    def steps_per_epoch(self) -> int:
        return len(self.partitioner) // self.batch_size

    def _augment(self, x: np.ndarray,
                 rng: np.random.Generator) -> np.ndarray:
        """Fused pad+crop+flip on uint8. RNG draws happen here (numpy side)
        so the C++ and fallback paths are bit-identical; the pixel work runs
        in the native library when built (gtopkssgd_tpu.native)."""
        from gtopkssgd_tpu import native

        b = x.shape[0]
        ys = rng.integers(0, 9, b).astype(np.int32)
        xs = rng.integers(0, 9, b).astype(np.int32)
        flips = rng.random(b) < 0.5
        return native.cifar_augment_batch(x, ys, xs, flips)

    def epoch(self, epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        """One pass over this rank's shard, in the shared per-epoch order.
        Batches are raw uint8 either way; normalization is on-device.

        Augmentation draws come from a generator seeded by (seed, rank,
        epoch) created HERE, so batch b of epoch e is a pure function of
        those four values — not of how many batches some other consumer
        (the prefetcher, a shape-probing peek, a pre-restore iterator)
        happened to pull first. Mid-epoch checkpoint resume depends on
        this: the trainer re-drains epoch e to the restored step and must
        land on bit-identical batches (same idea as the ImageNet decode
        pool's per-image (seed, split, epoch, index) keying)."""
        idx = self.partitioner.indices(epoch)
        rng = np.random.default_rng(
            np.random.SeedSequence([self._seed, self._rank + 1, epoch]))
        for lo in range(0, len(idx) - self.batch_size + 1, self.batch_size):
            sel = idx[lo:lo + self.batch_size]
            x = self.images[sel]
            if self.augment:
                x = self._augment(x, rng)
            yield {"image": x, "label": self.labels[sel]}

    def __iter__(self):
        """Endless stream across epochs (what the training loop consumes)."""
        e = 0
        while True:
            yield from self.epoch(e)
            e += 1
