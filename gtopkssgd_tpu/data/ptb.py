"""Penn Treebank pipeline (reference C8: the PTB text batcher with BPTT
windows inside dl_trainer.py).

Standard LM batching: concatenate the whole split into one token stream,
chop into ``batch_size`` parallel streams, slide a ``bptt``-token window; a
batch is (tokens i32[B, T], targets i32[B, T]) with targets = tokens shifted
by one. Hidden state carries across consecutive windows (the trainer resets
it at epoch boundaries), which is why sharding is over *stream rows*: each
rank owns batch_size contiguous rows of a batch_size*nworkers-row corpus so
its windows stay temporally consecutive — the reference partitioned PTB the
same way (a rank must see its own rows every step for the carry to be valid).

Real path reads ``ptb.{train,valid,test}.txt`` (word-level, vocab built from
train). Synthetic fallback: a Zipf-distributed token stream over the full
10k vocab.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Iterator

import numpy as np

from gtopkssgd_tpu.data.partition import split_id as _split_id

VOCAB_SIZE = 10000
SYNTH_TOKENS = {"train": 200_000, "valid": 40_000, "test": 40_000}


@functools.lru_cache(maxsize=4)
def _synth_tokens(split: str, seed: int) -> np.ndarray:
    """Zipf token stream; cached so P rank objects share one array, seeded
    stably (crc32, not hash()) so every process derives the same corpus."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, _split_id(split)]))
    stream = rng.zipf(1.3, SYNTH_TOKENS[split]).astype(np.int64)
    return np.clip(stream, 1, VOCAB_SIZE - 1).astype(np.int32)


class PTBDataset:
    bptt_default = 35

    def __init__(self, *, split="train", batch_size=20, rank=0, nworkers=1,
                 data_dir=None, seed=0, bptt=35):
        self.split = "valid" if split in ("val", "valid") else split
        self.batch_size = batch_size
        self.bptt = bptt
        path = os.path.join(data_dir or "", f"ptb.{self.split}.txt")
        self.synthetic = not os.path.isfile(path)
        if self.synthetic:
            self.tokens = _synth_tokens(self.split, seed)
            self.vocab_size = VOCAB_SIZE
            self.vocab = None
        else:
            self.vocab = self._build_vocab(
                os.path.join(data_dir or "", "ptb.train.txt")
            )
            self.vocab_size = len(self.vocab)
            self.tokens = self._tokenize(path)
        # Global layout: (batch_size * nworkers) rows; this rank owns rows
        # [rank*B, (rank+1)*B). Rows are contiguous token spans => carry valid.
        rows = batch_size * nworkers
        total = (len(self.tokens) - 1) // rows * rows
        usable = self.tokens[: total + 1]
        self.row_len = total // rows
        grid = usable[:-1].reshape(rows, self.row_len)
        tgt = usable[1:].reshape(rows, self.row_len)
        lo, hi = rank * batch_size, (rank + 1) * batch_size
        self.inputs = grid[lo:hi]
        self.targets = tgt[lo:hi]
        if self.row_len < self.bptt:
            raise ValueError(
                f"rows of {self.row_len} tokens are shorter than one "
                f"bptt window ({self.bptt}) — lower batch_size or nworkers"
            )

    @staticmethod
    def _build_vocab(train_path: str):
        words = open(train_path).read().replace("\n", " <eos> ").split()
        vocab = {"<unk>": 0}
        for w in sorted(set(words)):
            vocab.setdefault(w, len(vocab))
        return vocab

    def _tokenize(self, path: str) -> np.ndarray:
        words = open(path).read().replace("\n", " <eos> ").split()
        unk = self.vocab.get("<unk>", 0)
        return np.asarray([self.vocab.get(w, unk) for w in words], np.int32)

    def steps_per_epoch(self) -> int:
        return self.row_len // self.bptt

    def epoch(self, epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        for lo in range(0, self.row_len - self.bptt + 1, self.bptt):
            yield {
                "tokens": self.inputs[:, lo:lo + self.bptt],
                "targets": self.targets[:, lo:lo + self.bptt],
            }

    def __iter__(self):
        e = 0
        while True:
            yield from self.epoch(e)
            e += 1
