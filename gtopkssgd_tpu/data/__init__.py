"""Data pipelines (reference C8: the dataset builders inside dl_trainer.py
plus the AN4 audio loader files).

Four dataset families matching the reference workloads — CIFAR-10, ImageNet,
PTB, AN4 — each with:

  * deterministic per-rank sharding (reference ``DataPartitioner``:
    every rank sees a disjoint 1/P slice of the epoch, reshuffled per epoch
    from a shared seed so replicas stay in lockstep);
  * a **synthetic fallback** when ``data_dir`` has no real data, so every
    pipeline (and CI, and the benchmark harness) runs in a zero-egress
    environment with identical shapes/dtypes to the real thing;
  * host-side numpy batches handed to jax at the step boundary (on TPU the
    transfer overlaps with the previous step; the native C++ reader in
    gtopkssgd_tpu/native accelerates the real-file path).

``get_dataset`` mirrors the reference's ``--dataset`` flag dispatch.
"""

from __future__ import annotations

from typing import Any, Dict

from gtopkssgd_tpu.data.an4 import AN4Dataset
from gtopkssgd_tpu.data.cifar import CIFAR10Dataset
from gtopkssgd_tpu.data.imagenet import ImageNetDataset
from gtopkssgd_tpu.data.partition import DataPartitioner, partition_indices
from gtopkssgd_tpu.data.ptb import PTBDataset

_DATASETS = {
    "cifar10": CIFAR10Dataset,
    "imagenet": ImageNetDataset,
    "ptb": PTBDataset,
    "an4": AN4Dataset,
}


def get_dataset(
    name: str,
    *,
    split: str = "train",
    batch_size: int = 32,
    rank: int = 0,
    nworkers: int = 1,
    data_dir: str | None = None,
    seed: int = 0,
    **kwargs: Any,
):
    """Build a dataset by its reference ``--dataset`` flag string.

    ``batch_size`` is per-worker (reference semantics: the global batch is
    batch_size * nworkers). ``rank``/``nworkers`` select this worker's shard.
    """
    try:
        cls = _DATASETS[name]
    except KeyError:
        raise ValueError(
            f"unknown dataset {name!r}; available: {sorted(_DATASETS)}"
        ) from None
    return cls(
        split=split,
        batch_size=batch_size,
        rank=rank,
        nworkers=nworkers,
        data_dir=data_dir,
        seed=seed,
        **kwargs,
    )


def available_datasets():
    return sorted(_DATASETS)


__all__ = [
    "get_dataset",
    "available_datasets",
    "DataPartitioner",
    "partition_indices",
    "CIFAR10Dataset",
    "ImageNetDataset",
    "PTBDataset",
    "AN4Dataset",
]
