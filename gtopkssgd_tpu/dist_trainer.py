"""Distributed training driver + CLI (reference L4/L5: dist_trainer.py's
``main()`` — MPI init, rank→GPU bind, param broadcast, iteration loop —
plus the mpirun launch scripts' flag surface).

TPU-native redesign: there is no per-rank process and no broadcast — ONE
SPMD program spans the mesh. ``jax.distributed.initialize()`` (multi-host)
replaces ``MPI.COMM_WORLD`` init; device binding is the mesh; the initial
"broadcast params from rank 0" is implicit (replicated init from one seed);
the iteration loop with throughput logging lives in Trainer.fit.

Flags keep the reference's names (--dnn, --dataset, --density,
--compression, --nworkers, --nsteps-update, --batch-size, --max-epochs,
--data-dir) so reference experiment scripts translate 1:1:

    mpirun -np 8 python dist_trainer.py --dnn resnet20 --density 0.001
becomes
    python -m gtopkssgd_tpu.dist_trainer --dnn resnet20 --density 0.001 \
        --nworkers 8

Wire-format flag (parallel.codec — no reference equivalent; the MPI
reference always shipped fp32 values + int32 indices):

    --wire-codec CODEC                   on-wire sparse-set encoding for
                                         every exchange round. Grammar:
                                         fp32 (identity, default) |
                                         int8[:BLOCK] | fp8[:BLOCK] —
                                         block-scaled 8-bit values (bf16
                                         scales, BLOCK defaults to 64)
                                         + Elias-Fano bitpacked indices;
                                         quantization error folds into
                                         the error-feedback residual.
                                         Recorded in the run manifest;
                                         audit measured-vs-modeled bytes
                                         with ``report ledger``

Comm-planner flag (parallel.planner — no reference equivalent; the MPI
reference hand-picked its one tree):

    --comm-plan PLAN                     wire-plan pin. 'auto' (default)
                                         scores every schedule that
                                         realizes --compression with
                                         the alpha-beta model (newest
                                         dcn_probe alpha_beta_fit when
                                         present, documented fallback
                                         constants otherwise) and keeps
                                         the historical schedule on
                                         ties, so defaults never change
                                         the wire. Plan grammar: tree
                                         (hypercube) | balanced (the
                                         Ok-Topk split-and-reduce,
                                         arXiv:2201.07598) for gtopk /
                                         gtopk_layerwise; allgather,
                                         hier, dense name their modes'
                                         single schedule. The decision
                                         (chosen plan + every
                                         candidate's score) is the
                                         'plan' metrics record —
                                         ``report plan`` prints it —
                                         and the manifest carries
                                         comm_plan / comm_plan_schedule
                                         so the ledger audits the
                                         schedule that actually ran.

Bucketing flag (parallel.bucketing — no reference equivalent; the MPI
reference merged layer-by-layer with no cost model):

    --buckets SPEC                       gtopk_layerwise gradient
                                         bucketing. Grammar: concat
                                         (default — historical wire:
                                         per-leaf selection, ONE
                                         concatenated merge) | leaf
                                         (one merge per param leaf) |
                                         an int B | auto. B/auto
                                         partition the leaves into
                                         contiguous byte-balanced
                                         buckets by an exact DP over
                                         the alpha-beta model (cost
                                         B*alpha + wire_bytes/beta;
                                         'auto' also picks B), then run
                                         one fused two-stage selection
                                         and one codec-framed merge per
                                         bucket, scattering update and
                                         error-feedback residual back
                                         to the leaves. Boundaries are
                                         stamped into the manifest
                                         (bucket_boundaries/_sizes/_ks)
                                         and logged as the 'bucket'
                                         record; ``report plan`` prints
                                         them with modeled ms for
                                         B in {1, chosen, L}.

    --pipeline SPEC                      bucketed layerwise only: bucket
                                         execution order. Grammar:
                                         serial (default — the paper's
                                         sequential select->merge
                                         chain, pinned with
                                         optimization barriers) |
                                         overlap (double-buffered
                                         stages: bucket b+1's fused
                                         selection runs concurrently
                                         with bucket b's codec-framed
                                         merge; bit-identical to
                                         serial) | auto (cheaper
                                         modeled pipeline span wins;
                                         also switches --buckets auto
                                         to overlap pricing, where the
                                         DP objective is the per-stage
                                         max(T_select, T_merge) — so
                                         'auto auto' can pick a larger
                                         B than serial pricing would).
                                         The resolved order is stamped
                                         into the manifest/'plan'/
                                         'bucket' records and carried
                                         by ``report history`` /
                                         ``report regress``; 'report
                                         attr' measures the realized
                                         overlap_frac from the trace.

Observability flags (obs subsystem — no reference equivalent; the
reference's only telemetry was text logs):

    --obs-counters / --no-obs-counters   on-device compression counters
                                         (achieved density, tau, grad/
                                         residual norms, wire bytes) as
                                         per-step "obs" records (default on)
    --obs-interval N                     log "obs" every N steps (reading
                                         counters syncs on the step; raise
                                         to preserve dispatch overlap)
    --obs-layers                         per-layer compression telemetry
                                         (density, tau, norms, residual
                                         age, mass-capture m(k)) as one
                                         "layers" record per layer per obs
                                         step (default off; adds [L]-sized
                                         optimizer state)
    --obs-audit-interval N               every N steps, audit the
                                         production top-k selection against
                                         the exact top-k (recall in the
                                         "obs" record's audit_recall;
                                         0 = off)
    --obs-watchdog SECONDS               dispatch stall watchdog: fail fast
                                         with a structured diagnostic (exit
                                         43) instead of hanging forever on
                                         a dead accelerator tunnel (0 = off)
    --obs-events / --no-obs-events       online anomaly monitor over the
                                         synced loss/telemetry (NaN/Inf
                                         loss, EWMA loss spike, density
                                         collapse vs rho, residual blow-up
                                         and age runaway) emitting fsync'd
                                         severity-tagged "event" records
                                         (default on)
    --obs-halt-on {error,warn}           fail fast (exit 44) when an
                                         anomaly event of at least this
                                         severity fires; default: record
                                         only, never halt
    --obs-timeline PATH                  write the host-side Chrome-trace
                                         timeline (Tracer spans, telemetry
                                         counter tracks, event/stall
                                         markers) to PATH on exit — open
                                         in chrome://tracing or Perfetto
    --obs-export-port PORT               serve the latest metric values as
                                         OpenMetrics text on localhost
                                         (curl localhost:PORT/metrics);
                                         0 = off (default), -1 = ephemeral
    --obs-calib / --no-obs-calib         live comm-model calibration
                                         (obs.calib): profile-attribute a
                                         dispatch every
                                         --obs-calib-interval steps, fit
                                         alpha/beta online from measured
                                         (wire_bytes, t_comm) with a
                                         robust (median-of-slopes)
                                         estimator; 'calib' records per
                                         refit, comm_model_drift anomaly
                                         vs the planner's inputs, and an
                                         end-of-run calib_fit_{P}proc
                                         .json artifact the next run's
                                         planner consumes (default off —
                                         each sample costs a capture)
    --obs-calib-interval N               steps between calibration
                                         captures (default 25)
    --obs-critpath / --no-obs-critpath   per-step stage-interval records
                                         (obs.critpath): profile-attribute
                                         a dispatch every
                                         --obs-calib-interval steps
                                         (shares the calibrator's capture
                                         when both are on) into ordered
                                         {stage, t0, t1} segments with
                                         the comm span split into wire
                                         vs skew-wait by the ledger's
                                         alpha-beta model; one durable
                                         'critpath' record per sample,
                                         joined across ranks by
                                         `report critpath` into the
                                         global critical path
                                         (default off)
    --obs-critpath-shift-windows K       consecutive joined steps whose
                                         critical stage differs from the
                                         established modal stage before
                                         the critpath_shift anomaly
                                         fires (default 3)
    --obs-mem / --no-obs-mem             compile/memory-plane watch
                                         (obs.memwatch): AOT compile
                                         accounting — one fsync'd
                                         "compile" record per distinct
                                         dispatch shape, peak-HBM
                                         estimate stamped into the
                                         manifest — plus jit-cache
                                         recompile tracking
                                         (recompile_storm rule) and
                                         sampled live-memory "mem"
                                         records feeding the
                                         device_mem_leak / hbm_headroom
                                         rules (default off — costs one
                                         AOT compile per dispatch shape)
    --obs-mem-interval N                 steps between live-memory
                                         samples (default 50)
    --obs-recompile-warmup N             compile-watch polls before
                                         recompile_storm arms (default
                                         1; 0 = any cache growth fires)
    --obs-mem-leak-windows K             consecutive growing live-bytes
                                         windows before device_mem_leak
                                         fires (default 3)
    --obs-hbm-headroom-frac F            bytes_in_use/bytes_limit
                                         fraction above which
                                         hbm_headroom fires (default
                                         0.92)
    --obs-goodput / --no-obs-goodput     goodput/badput wall-clock
                                         ledger (obs.goodput): partition
                                         the run's measured wall into
                                         productive step compute vs the
                                         closed badput taxonomy (select,
                                         comm, wait, compile, ckpt,
                                         wasted, degraded, data,
                                         startup), unattributed
                                         remainder surfaced as
                                         other_frac (conservation: the
                                         categories sum to wall by
                                         construction). Pure host
                                         arithmetic at sync points the
                                         loop already pays — default on.
                                         Inspect with 'report goodput'
                                         (per-rank bars, --compare,
                                         --advise eviction hint)
    --obs-goodput-interval N             optimizer steps between
                                         periodic durable 'goodput'
                                         records (default 50; <= 0
                                         keeps only the end-of-run
                                         summary). Each record feeds
                                         the goodput_collapse rule
    --obs-goodput-collapse-windows K     consecutive ledger records
                                         with goodput_frac below half
                                         its own EWMA before the
                                         goodput_collapse anomaly fires
                                         (default 3; honors
                                         --obs-halt-on like every rule)
    --obs-linkmap / --no-obs-linkmap     per-(axis, peer) network
                                         weather map (obs.linkmap):
                                         carve each calibration
                                         capture's measured comm span
                                         over the schedule's
                                         round->peer join, keep EWMA
                                         latency/bandwidth per link,
                                         log one durable 'linkmap'
                                         record per capture. Needs
                                         --obs-calib (rides its
                                         cadence); default off.
                                         Inspect with 'report linkmap'
    --obs-link-degraded-x X              one link's EWMA latency above
                                         X times the fleet median
                                         counts as a degraded window
                                         (default 4.0)
    --obs-link-degraded-windows K        consecutive degraded windows
                                         before the link_degraded
                                         anomaly fires (default 3; a
                                         recovered window re-arms;
                                         honors --obs-halt-on)
    --obs-forecast / --no-obs-forecast   scale-out forecast plane
                                         (obs.forecast): hindcast the
                                         analytic step model against
                                         this run each calibration
                                         capture, forecast step time /
                                         goodput at the P targets
                                         across schedules and axis
                                         trees, one durable 'forecast'
                                         record per capture. Needs
                                         --obs-calib (rides its
                                         cadence); default off.
                                         Inspect with 'report forecast'
    --obs-forecast-targets LIST          comma-separated modeled worker
                                         counts the forecast grid
                                         prices (default 32,256,1024)
    --obs-forecast-drift-x X             hindcast error factor beyond
                                         which a capture counts as
                                         drifted; 3 consecutive drifted
                                         captures fire forecast_drift
                                         (default 4.0; honors
                                         --obs-halt-on)
    --registry DIR                       append one summary line per run
                                         to DIR/runs.jsonl (obs.registry:
                                         manifest header + steps/sec,
                                         comm ratio, fitted alpha/beta,
                                         recall floor, wire bytes/step);
                                         read back with 'report history' /
                                         'report regress'
    --comm-model-fit PATH                explicit alpha/beta artifact
                                         (dcn_probe_*.json or
                                         calib_fit_*.json) pricing the
                                         comm planner, with the filename
                                         stamped as fit provenance in the
                                         manifest and the decided
                                         schedule pinned into the
                                         optimizer

Resilience flags (gtopkssgd_tpu/resilience — turn detect-and-halt into
detect-and-recover):

    --inject SPEC                        deterministic step-keyed fault
                                         injection (nan_grad@K,
                                         slow_rank:R:DURs@A-B,
                                         loader_raise@K, preempt@K,
                                         corrupt_ckpt@latest, reshape@K
                                         — a changed dispatch shape
                                         that forces a retrace)
    --recover-policy POLICY              rule=action[:budget[:param]] maps
                                         anomaly rules to skip / rollback /
                                         degrade instead of exit 44
    --preempt-save / --no-preempt-save   SIGTERM/SIGINT -> emergency
                                         step-granular checkpoint -> exit
                                         45; resume with --resume
    --allow-ckpt-mismatch                restore past a config_hash/state-
                                         digest integrity mismatch
    --elastic / --no-elastic             elastic fleet (resilience/
                                         elastic.py): a membership
                                         change — preemption, a
                                         goodput-advised eviction, or
                                         an injected resize@K:NEWP /
                                         evict_rank:R@K — drains to a
                                         step boundary, emergency-saves
                                         (sidecar meta records the
                                         residual partition width),
                                         rewrites out-dir/elastic.json
                                         (lineage_id + resize_epoch),
                                         logs a durable "resize"
                                         record, and exits 46; relaunch
                                         with --resume --elastic and
                                         the new --nworkers. The resume
                                         re-partitions the dp-sharded
                                         error-feedback residual onto
                                         the new P (grow = zero rows,
                                         shrink = masked-fold addition
                                         conserving the pending
                                         gradient mass) and re-derives
                                         planner/bucketing/calibration
                                         at the new size. Both sides of
                                         a resize must pass --elastic
    --evict-after-windows K              elastic: self-check the merged
                                         per-rank goodput/straggler
                                         view every K goodput windows
                                         and evict the rank
                                         eviction_decision names
                                         (default 3; 0 disables the
                                         automatic check)
    --min-fleet N                        elastic: never resize below N
                                         workers (default 1; a refused
                                         preemption-resize falls back
                                         to classic exit-45 semantics)

Exit codes come from the single-source registry
``gtopkssgd_tpu/exit_codes.py`` (0 ok, 43 stall watchdog, 44 anomaly
halt, 45 preempted-after-save, 46 elastic-resize restart, 99 multihost
designed skip — see that module for the full table; graftlint's
exit-code rule rejects literals minted anywhere else).

Summarize or diff the resulting metrics.jsonl with
``python -m gtopkssgd_tpu.obs.report <out-dir> [<other-out-dir>]``.
Multi-host runs shard metrics per rank (metrics.rank{r}.jsonl); merge
them with ``python -m gtopkssgd_tpu.obs.report fleet <out-dir>`` and
tail a live run with ``... report watch <out-dir>``.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

import jax

from gtopkssgd_tpu.trainer import TrainConfig, Trainer


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "gtopkssgd_tpu.dist_trainer",
        description="gTop-k S-SGD training on TPU (SPMD over a dp mesh)",
    )
    p.add_argument("--dnn", default="resnet20")
    p.add_argument("--dataset", default=None)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-worker batch size (global = batch*nworkers)")
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=None)
    p.add_argument("--nesterov", action="store_true")
    p.add_argument("--compression", default=None,
                   choices=["none", "dense", "gtopk", "allgather", "topk",
                            "gtopk_hier", "gtopk_layerwise"],
                   help="None/dense = psum baseline; gtopk = tree sparse "
                        "allreduce; allgather/topk = DGC-style union; "
                        "gtopk_layerwise = per-layer top-k + per-layer "
                        "error feedback (flat gradient never materializes); "
                        "gtopk_hier = dense within ICI slice, gtopk across "
                        "slices (set --hier-ici)")
    p.add_argument("--density", type=float, default=0.001)
    p.add_argument("--hier-ici", type=int, default=1,
                   help="gtopk_hier: devices per ICI slice (dense psum "
                        "within each contiguous block of this many ranks, "
                        "gTop-k hypercube across the nworkers/hier_ici "
                        "slices)")
    p.add_argument("--topk-method", default="auto",
                   choices=["auto", "exact", "blockwise", "approx",
                            "threshold", "pallas", "twostage",
                            "simrecall"])
    p.add_argument("--wire-codec", default="fp32",
                   help="on-wire sparse-set codec for every exchange "
                        "round: fp32 (identity), int8[:BLOCK] or "
                        "fp8[:BLOCK] (block-scaled values, bf16 scales, "
                        "Elias-Fano bitpacked indices; BLOCK defaults "
                        "to 64). Quantization error folds into the "
                        "error-feedback residual")
    p.add_argument("--comm-plan", default="auto",
                   help="wire-plan pin (parallel.planner). 'auto' "
                        "(default) scores every schedule that realizes "
                        "--compression with the alpha-beta model "
                        "(dcn_probe fit when present) and keeps the "
                        "historical schedule on ties; a plan name pins "
                        "it: tree | balanced (Ok-Topk split-and-reduce) "
                        "for gtopk/gtopk_layerwise, allgather / hier / "
                        "dense for their modes. Decision is logged as "
                        "the 'plan' record (``report plan``) and "
                        "stamped into the run manifest")
    p.add_argument("--buckets", default="concat",
                   help="gtopk_layerwise only: gradient bucketing "
                        "(parallel.bucketing). 'concat' (default) keeps "
                        "the historical wire — per-leaf selection, one "
                        "concatenated merge; 'leaf' runs one merge per "
                        "param leaf; an int B or 'auto' partitions the "
                        "leaves into contiguous byte-balanced buckets "
                        "('auto' picks B itself) by an exact alpha-beta "
                        "DP — cost B*alpha + wire_bytes/beta — and runs "
                        "one fused selection + one codec-framed merge "
                        "per bucket. Boundaries are stamped into the "
                        "manifest and logged as the 'bucket' record "
                        "(``report plan`` prints them)")
    p.add_argument("--pipeline", default="serial",
                   help="bucketed layerwise only: bucket execution "
                        "order. 'serial' (default) pins the paper's "
                        "sequential select->merge chain; 'overlap' "
                        "double-buffers the stages so bucket b+1's "
                        "selection runs under bucket b's merge — "
                        "bit-identical to serial; 'auto' picks the "
                        "cheaper modeled span and prices --buckets "
                        "auto with the overlap objective. Requires a "
                        "bucketed wire (--buckets != concat) for "
                        "'overlap'")
    p.add_argument("--clip-grad-norm", type=float, default=None)
    p.add_argument("--steps-per-dispatch", type=int, default=1,
                   help="optimizer steps per jitted dispatch (lax.scan "
                        "on-device); >1 amortizes per-step dispatch "
                        "cost for small models")
    p.add_argument("--nsteps-update", type=int, default=1,
                   help="gradient accumulation micro-steps per comm round")
    p.add_argument("--max-epochs", type=int, default=140)
    p.add_argument("--warmup-epochs", type=int, default=0,
                   help="linear LR ramp over the first N epochs")
    p.add_argument("--dense-warmup-epochs", type=int, default=0,
                   help="sparse modes: communicate dense for the first N "
                        "epochs before enabling top-k (warm-up training)")
    p.add_argument("--momentum-correction", action="store_true",
                   help="sparse modes: DGC momentum correction + factor "
                        "masking — velocity accumulates locally BEFORE "
                        "selection (arXiv:1712.01887 s3, TPU extension)")
    p.add_argument("--nworkers", type=int, default=0,
                   help="mesh size (0 = all visible devices)")
    p.add_argument("--data-dir", default=None)
    p.add_argument("--out-dir", default=None)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--s2d", action="store_true",
                   help="resnet50: space-to-depth stem (4x4x12 conv on 2x2 "
                        "pixel blocks; a superset of the 7x7x3 map — exact "
                        "embedding test-pinned — at MXU-friendly channel "
                        "width)")
    p.add_argument("--num-iters", type=int, default=None,
                   help="train a fixed number of steps instead of epochs")
    p.add_argument("--synth-hard", action="store_true",
                   help="synthetic CIFAR only: the discriminative variant "
                        "(weak spatial class signal + 10%% train label "
                        "noise; data/cifar.py) — arms can separate on "
                        "val accuracy instead of saturating at 1.0")
    p.add_argument("--eval-batches", type=int, default=None)
    p.add_argument("--log-interval", type=int, default=50)
    p.add_argument("--prefetch", type=int, default=2,
                   help="host batches assembled ahead by a background "
                        "thread (0 = synchronous assembly)")
    p.add_argument("--decode-workers", type=int, default=0,
                   help="ImageNet real-file path: decode worker processes "
                        "(reference DataLoader num_workers; ~280 img/s per "
                        "core vs ~6.8k img/s per v5e chip at bs=128 — see "
                        "benchmarks/results/input_path_1core_host.json)")
    p.add_argument("--obs-counters", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="on-device compression/comm counters logged as "
                        "per-step 'obs' records (--no-obs-counters traces "
                        "the step exactly as before the obs subsystem)")
    p.add_argument("--obs-interval", type=int, default=1,
                   help="log an 'obs' record every N optimizer steps; "
                        "reading counters syncs on the dispatched step, "
                        "so raise this to keep async dispatch overlap")
    p.add_argument("--obs-layers", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="per-layer compression-quality telemetry "
                        "(obs.counters.LAYER_FIELDS) logged as one "
                        "'layers' record per layer per obs step; opt-in "
                        "because it adds [L]-sized optimizer state "
                        "(checkpoint treedef change) and a few segment "
                        "reductions to the jitted step")
    p.add_argument("--obs-audit-interval", type=int, default=0,
                   help="every N optimizer steps, audit the production "
                        "top-k selection against the exact top-k of the "
                        "accumulator (ops.topk exact path); recall lands "
                        "in the 'obs' record's audit_recall field "
                        "(-1 = never audited); 0 disables")
    p.add_argument("--obs-watchdog", type=float, default=0.0,
                   help="seconds a dispatched step may go without host-"
                        "visible progress before the stall watchdog dumps "
                        "a structured diagnostic and exits 43 (0 = off); "
                        "set well above log-interval * step time")
    p.add_argument("--obs-events", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="online anomaly monitor (obs.events): NaN/Inf "
                        "loss, EWMA loss spike, achieved-density collapse "
                        "vs rho, residual blow-up/age runaway — each "
                        "firing logs a severity-tagged fsync'd 'event' "
                        "record at the obs/log sync points (no extra "
                        "device reads)")
    p.add_argument("--obs-halt-on", default=None,
                   choices=["error", "warn"],
                   help="fail fast when an anomaly event of at least this "
                        "severity fires: the event record is flushed, "
                        "then the run exits 44 (the stall watchdog owns "
                        "43); default records without halting")
    p.add_argument("--obs-timeline", default=None, metavar="PATH",
                   help="write the host-side Chrome-trace timeline "
                        "(obs.timeline: Tracer spans, telemetry counter "
                        "tracks, event/stall markers) here on exit; view "
                        "in chrome://tracing or ui.perfetto.dev. Rebuild "
                        "one later from metrics.jsonl with 'python -m "
                        "gtopkssgd_tpu.obs.report timeline <out-dir>'")
    p.add_argument("--obs-export-port", type=int, default=0,
                   help="serve the latest metric values as OpenMetrics "
                        "text on this localhost HTTP port "
                        "(obs.exporter; curl localhost:PORT/metrics); "
                        "0 disables (default), -1 binds an ephemeral "
                        "port (logged at startup)")
    p.add_argument("--obs-calib", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="live comm-model calibration (obs.calib): every "
                        "--obs-calib-interval steps, profile-attribute "
                        "one dispatch and feed measured (wire_bytes, "
                        "t_comm) to an online robust alpha/beta fitter — "
                        "'calib' records per refit, a comm_model_drift "
                        "anomaly when the live fit diverges from the "
                        "planner's inputs, and an end-of-run "
                        "calib_fit_{P}proc.json artifact in out-dir that "
                        "the next run's planner can consume. Opt-in: "
                        "each sample costs a profiler capture + sync")
    p.add_argument("--obs-calib-interval", type=int, default=25,
                   help="optimizer steps between calibration captures")
    p.add_argument("--obs-critpath",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="per-step stage-interval records (obs.critpath): "
                        "every --obs-calib-interval steps, "
                        "profile-attribute one dispatch into ordered "
                        "{stage, t0, t1} segments, splitting the comm "
                        "span into wire vs skew-wait via the ledger's "
                        "alpha-beta model, and log a durable 'critpath' "
                        "record; `report critpath` joins the per-rank "
                        "records into the global critical path. Opt-in: "
                        "each sample costs a profiler capture + sync")
    p.add_argument("--obs-critpath-shift-windows", type=int, default=3,
                   help="consecutive joined steps whose critical stage "
                        "differs from the established modal stage "
                        "before the critpath_shift anomaly fires")
    p.add_argument("--obs-mem", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="compile/memory-plane watch (obs.memwatch): AOT "
                        "compile accounting — one fsync'd 'compile' record "
                        "per distinct dispatch shape (cost/memory analysis, "
                        "lower/compile wall times) with the peak-HBM "
                        "estimate stamped into the manifest — plus jit "
                        "executable-cache recompile tracking (the "
                        "recompile_storm rule) and sampled live-memory "
                        "'mem' records (jax.live_arrays + per-device "
                        "memory_stats) feeding device_mem_leak / "
                        "hbm_headroom. Opt-in: costs one ahead-of-time "
                        "compile per dispatch shape at startup")
    p.add_argument("--obs-mem-interval", type=int, default=50,
                   help="optimizer steps between live-memory samples")
    p.add_argument("--obs-recompile-warmup", type=int, default=1,
                   help="compile-watch polls before recompile_storm arms "
                        "(0 fires on any executable-cache growth; the "
                        "default tolerates the first real dispatch)")
    p.add_argument("--obs-mem-leak-windows", type=int, default=3,
                   help="consecutive growing live-memory windows before "
                        "device_mem_leak fires")
    p.add_argument("--obs-hbm-headroom-frac", type=float, default=0.92,
                   help="bytes_in_use/bytes_limit fraction above which "
                        "hbm_headroom fires (backends without "
                        "memory_stats never trip it)")
    p.add_argument("--obs-goodput", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="goodput/badput wall-clock ledger (obs.goodput): "
                        "partition measured wall into productive step "
                        "compute vs the badput taxonomy (select/comm/"
                        "wait/compile/ckpt/wasted/degraded/data/startup) "
                        "with the unattributed remainder surfaced as "
                        "other_frac; cumulative durable 'goodput' "
                        "records + an end-of-run summary. Host-side "
                        "arithmetic only — default on; inspect with "
                        "'report goodput'")
    p.add_argument("--obs-goodput-interval", type=int, default=50,
                   help="optimizer steps between periodic durable "
                        "'goodput' records (<= 0 keeps only the "
                        "end-of-run summary); each record feeds the "
                        "goodput_collapse rule")
    p.add_argument("--obs-goodput-collapse-windows", type=int, default=3,
                   help="consecutive ledger records with goodput_frac "
                        "below half its own EWMA before goodput_collapse "
                        "fires (honors --obs-halt-on)")
    p.add_argument("--obs-linkmap", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="per-(axis, peer) network weather map "
                        "(obs.linkmap): carve each calibration capture's "
                        "measured comm span over the schedule's "
                        "round->peer join into per-link EWMA latency/"
                        "bandwidth, one durable 'linkmap' record per "
                        "capture, feeding the link_degraded rule. Needs "
                        "--obs-calib (rides its cadence); inspect with "
                        "'report linkmap'")
    p.add_argument("--obs-link-degraded-x", type=float, default=4.0,
                   help="one link's EWMA latency above this multiple of "
                        "the fleet median counts as a degraded window")
    p.add_argument("--obs-link-degraded-windows", type=int, default=3,
                   help="consecutive degraded windows before "
                        "link_degraded fires (a recovered window "
                        "re-arms; honors --obs-halt-on)")
    p.add_argument("--obs-forecast",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="scale-out forecast plane (obs.forecast): "
                        "hindcast the analytic step model against this "
                        "run each calibration capture, then forecast "
                        "step time/goodput at the P targets across "
                        "schedules and axis trees — one durable "
                        "'forecast' record per capture, feeding the "
                        "forecast_drift rule. Needs --obs-calib (rides "
                        "its cadence); inspect with 'report forecast'")
    p.add_argument("--obs-forecast-targets", default="32,256,1024",
                   metavar="LIST",
                   help="comma-separated modeled worker counts the "
                        "forecast grid prices")
    p.add_argument("--obs-forecast-drift-x", type=float, default=4.0,
                   help="hindcast error factor beyond which a capture "
                        "counts as drifted; 3 consecutive drifted "
                        "captures fire forecast_drift (honors "
                        "--obs-halt-on)")
    p.add_argument("--registry", default=None, metavar="DIR",
                   help="append this run's summary line (manifest subset "
                        "+ steps/sec, comm ratio, fitted alpha/beta, "
                        "recall floor, wire bytes/step) to DIR/runs.jsonl "
                        "on exit (obs.registry); inspect offline with "
                        "'report history DIR' and gate with 'report "
                        "regress OUT_DIR --registry DIR'")
    p.add_argument("--comm-model-fit", default=None, metavar="PATH",
                   help="explicit alpha/beta fit artifact (a dcn_probe_*"
                        ".json or calib_fit_*.json) pricing the comm "
                        "planner instead of the probe-dir lookup; the "
                        "filename lands in the manifest/plan record as "
                        "fit provenance and the decided schedule is "
                        "pinned through to the optimizer. A malformed "
                        "file fails at startup")
    p.add_argument("--inject", default=None, metavar="SPEC",
                   help="step-keyed fault injection (resilience subsystem; "
                        "grammar KIND[:ARG...]@STEP|A-B|latest, comma-"
                        "separated): nan_grad@120 poisons the gradient at "
                        "step 120; slow_rank:2:2.5s@50-60 sleeps 2.5s per "
                        "step on rank 2; loader_raise@75 raises from the "
                        "data loader; preempt@200 delivers SIGTERM; "
                        "corrupt_ckpt@latest truncates the newest "
                        "checkpoint before restore; reshape@9 halves the "
                        "batch axis of step 9's host batch (forces a "
                        "retrace — recompile-storm chaos). Deterministic, "
                        "so chaos runs reproduce in CI")
    p.add_argument("--recover-policy", default=None, metavar="POLICY",
                   help="map anomaly rules to recovery actions instead of "
                        "exit 44 (grammar rule=action[:budget[:param]], "
                        "comma-separated; actions: skip, rollback, "
                        "degrade) — e.g. 'nan_loss=skip,"
                        "density_collapse=degrade:2:100'. Requires "
                        "--obs-events; unmapped rules keep halt semantics")
    p.add_argument("--allow-ckpt-mismatch", action="store_true",
                   help="restore a checkpoint whose recorded config_hash/"
                        "state digest disagrees with this run's (normally "
                        "refused: resuming under different flags silently "
                        "changes the experiment)")
    p.add_argument("--elastic", action=argparse.BooleanOptionalAction,
                   default=False,
                   help="elastic fleet (resilience/elastic.py): treat "
                        "membership changes (preemption, goodput-"
                        "advised eviction, injected resize@K:NEWP) as "
                        "a drain + checkpoint + lineage rewrite + exit "
                        "46 resize instead of run death; relaunch with "
                        "--resume --elastic at the new --nworkers and "
                        "the dp-sharded residual is re-partitioned "
                        "onto the new fleet (both sides of a resize "
                        "need this flag)")
    p.add_argument("--evict-after-windows", type=int, default=3,
                   help="elastic: self-check the merged per-rank "
                        "goodput/straggler view every this-many "
                        "--obs-goodput-interval windows and evict the "
                        "rank eviction_decision names (0 disables the "
                        "automatic check; injected evict_rank:R@K "
                        "still works)")
    p.add_argument("--min-fleet", type=int, default=1,
                   help="elastic: never resize below this many workers "
                        "(a preemption-resize that would falls back to "
                        "classic exit-45 preempt semantics)")
    p.add_argument("--preempt-save", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="intercept SIGTERM/SIGINT: forced step-granular "
                        "emergency checkpoint, then exit 45 (resume with "
                        "--resume); --no-preempt-save keeps the default "
                        "signal disposition")
    p.add_argument("--resume", action="store_true",
                   help="restore the latest checkpoint from out-dir")
    p.add_argument("--multihost", action="store_true",
                   help="call jax.distributed.initialize() first")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace (TensorBoard/Perfetto"
                        " format) of --profile-steps early training steps")
    p.add_argument("--profile-steps", type=int, default=10)
    return p


def config_from_args(args: argparse.Namespace) -> TrainConfig:
    nworkers = args.nworkers or jax.device_count()
    return TrainConfig(
        dnn=args.dnn,
        dataset=args.dataset,
        batch_size=args.batch_size,
        lr=args.lr,
        momentum=args.momentum,
        weight_decay=args.weight_decay,
        nesterov=args.nesterov,
        compression=args.compression,
        density=args.density,
        hier_ici=args.hier_ici,
        topk_method=args.topk_method,
        wire_codec=args.wire_codec,
        comm_plan=args.comm_plan,
        buckets=args.buckets,
        pipeline=args.pipeline,
        clip_grad_norm=args.clip_grad_norm,
        nsteps_update=args.nsteps_update,
        steps_per_dispatch=args.steps_per_dispatch,
        warmup_epochs=args.warmup_epochs,
        dense_warmup_epochs=args.dense_warmup_epochs,
        momentum_correction=args.momentum_correction,
        max_epochs=args.max_epochs,
        nworkers=nworkers,
        data_dir=args.data_dir,
        out_dir=args.out_dir,
        seed=args.seed,
        dtype=args.dtype,
        space_to_depth=args.s2d,
        synth_hard=args.synth_hard,
        eval_batches=args.eval_batches,
        log_interval=args.log_interval,
        obs_counters=args.obs_counters,
        obs_interval=args.obs_interval,
        obs_layers=args.obs_layers,
        obs_audit_interval=args.obs_audit_interval,
        obs_watchdog=args.obs_watchdog,
        obs_events=args.obs_events,
        obs_halt_on=args.obs_halt_on,
        obs_timeline=args.obs_timeline,
        obs_export_port=args.obs_export_port,
        obs_calib=args.obs_calib,
        obs_calib_interval=args.obs_calib_interval,
        obs_critpath=args.obs_critpath,
        obs_critpath_shift_windows=args.obs_critpath_shift_windows,
        obs_mem=args.obs_mem,
        obs_mem_interval=args.obs_mem_interval,
        obs_recompile_warmup=args.obs_recompile_warmup,
        obs_mem_leak_windows=args.obs_mem_leak_windows,
        obs_hbm_headroom_frac=args.obs_hbm_headroom_frac,
        obs_goodput=args.obs_goodput,
        obs_goodput_interval=args.obs_goodput_interval,
        obs_goodput_collapse_windows=args.obs_goodput_collapse_windows,
        obs_linkmap=args.obs_linkmap,
        obs_link_degraded_x=args.obs_link_degraded_x,
        obs_link_degraded_windows=args.obs_link_degraded_windows,
        obs_forecast=args.obs_forecast,
        obs_forecast_targets=args.obs_forecast_targets,
        obs_forecast_drift_x=args.obs_forecast_drift_x,
        registry=args.registry,
        comm_model_fit=args.comm_model_fit,
        inject=args.inject,
        recover_policy=args.recover_policy,
        allow_ckpt_mismatch=args.allow_ckpt_mismatch,
        elastic=args.elastic,
        evict_after_windows=args.evict_after_windows,
        min_fleet=args.min_fleet,
        prefetch=args.prefetch,
        decode_workers=args.decode_workers,
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    args = build_argparser().parse_args(argv)
    from gtopkssgd_tpu.exit_codes import EXIT_RESIZE_RESTART
    from gtopkssgd_tpu.resilience import (
        PREEMPT_EXIT_CODE,
        Preempted,
        PreemptionGuard,
        ResizeRestart,
        describe_policy,
        retry_call,
    )

    if args.multihost:
        # Multi-host pod slice / multislice: one process per host, same SPMD
        # program; ICI inside a slice, DCN across slices — both are just the
        # 'dp' axis to the program (reference: MPI.COMM_WORLD over ethernet).
        # Coordinator rendezvous races at pod startup (hosts come up in
        # arbitrary order) — the shared retry helper absorbs them.
        retry_call(jax.distributed.initialize, retries=3, delay=2.0,
                   desc="jax.distributed.initialize")
        # Announce this process's fleet identity up front — the same
        # process_index/count/coordinator triple lands in each shard's
        # run manifest (obs/manifest.py), which is how the fleet merger
        # validates that shards being merged belong to one run.
        from gtopkssgd_tpu.obs.manifest import coordinator_address

        print(f"[dist] process {jax.process_index()}/"
              f"{jax.process_count()} coordinator="
              f"{coordinator_address()} recovery="
              f"{describe_policy(args.recover_policy)}", flush=True)
    else:
        # The resolved policy is part of the run's identity — print it
        # where the operator (and the log scraper) will find it.
        print(f"[dist] recovery policy: "
              f"{describe_policy(args.recover_policy)}", flush=True)
    from gtopkssgd_tpu.obs.events import HALT_EXIT_CODE, AnomalyHalt

    with Trainer(config_from_args(args)) as trainer:
        guard = None
        if args.preempt_save:
            guard = PreemptionGuard(logger=trainer.logger).install()
            trainer.preempt = guard
        try:
            rc = _run(args, trainer)
            trainer.finalize_resilience("completed")
            return rc
        except AnomalyHalt as halt:
            # The monitor flushed the event record before raising; this
            # path only reports and maps to the contract exit code.
            trainer.logger.error("anomaly halt: %s", halt)
            trainer.finalize_resilience("halted")
            return HALT_EXIT_CODE
        except Preempted as why:
            # Emergency checkpoint already durable (_preempt_now saved
            # before raising); the exit code tells the harness to
            # relaunch with --resume.
            trainer.logger.warning("preempted: %s", why)
            trainer.finalize_resilience("preempted")
            return PREEMPT_EXIT_CODE
        except ResizeRestart as why:
            # Checkpoint, lineage file, and the durable "resize" record
            # all landed before the raise (_resize_now's contract); the
            # exit code tells the supervisor to relaunch at the new P
            # with --resume --elastic and the new --nworkers.
            trainer.logger.warning("elastic resize: %s", why)
            trainer.finalize_resilience("resized")
            return EXIT_RESIZE_RESTART
        finally:
            if guard is not None:
                guard.close()


def _run(args: argparse.Namespace, trainer: Trainer) -> int:
    if args.resume:
        restored = trainer.restore()
        trainer.logger.info("resume: %s",
                            "restored" if restored else "fresh")
    if args.profile_dir:
        # SURVEY.md §5 tracing: the reference only had host timer
        # dicts; here a real jax.profiler device trace complements
        # them. One dispatch first so compilation stays out of the
        # trace; step counts round up to whole dispatches so the
        # path composes with --steps-per-dispatch.
        spd = trainer.cfg.steps_per_dispatch
        warm = spd
        traced = max(spd, -(-args.profile_steps // spd) * spd)
        trainer.train(warm)
        jax.profiler.start_trace(args.profile_dir)
        trainer.train(traced)
        jax.profiler.stop_trace()
        trainer.logger.info("profiler: %d-step trace -> %s",
                            traced, args.profile_dir)
    if args.num_iters is not None:
        stats = trainer.train(args.num_iters)
        stats.update(trainer.test())
    else:
        stats = trainer.fit()
    trainer.logger.info("done: %s", stats)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
