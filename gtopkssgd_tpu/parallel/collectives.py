"""The three reduction modes of the reference allreducer, TPU-native.

Reference parity (allreducer.py in hclhkbu/gtopkssgd, SURVEY.md C5): three
modes behind one interface —

  (a) gTop-k tree  — log2(P) rounds of pairwise exchange of concatenated
      [values; indices] buffers, merge-then-reselect each round, then a
      reverse-tree broadcast (paper Algorithm 2).  O(k log P) per rank.
  (b) top-k allgather (DGC baseline)               O(k P) per rank.
  (c) dense allreduce                               O(N).

TPU redesign notes:

  * The reference tree is asymmetric (half the ranks go idle each round and
    rank 0 re-broadcasts down the tree — 2 log2 P total rounds).  SPMD wants
    symmetry, so we use the recursive-doubling (hypercube) formulation: at
    round r every device exchanges with `rank XOR 2^r` via `lax.ppermute` and
    both partners compute the identical merged top-k.  After log2(P) rounds
    every device holds the same global set — the reverse broadcast vanishes
    and total rounds HALVE vs the reference.  Equivalence: the merge
    (sparse-sum + reselect) is commutative and order-canonical
    (ops.topk.merge_sparse_sets), proven against a numpy oracle in
    tests/test_collectives.py.

  * All functions here run INSIDE a `jax.shard_map` body over the `dp` mesh
    axis — they are per-device views with collectives over `axis_name`.

  * gTop-k semantics (same as reference): the result is top-k of the
    *hierarchically merged partial sums*, which is not always exactly the
    top-k of the full dense sum — that approximation is the algorithm, and
    error feedback compensates (arXiv:1911.08772 analyzes why this
    converges).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from gtopkssgd_tpu.ops import merge_sparse_sets, scatter_add_dense, topk_abs

Array = jax.Array

# Re-exported for callers that reach collectives directly; the canonical
# definition lives in gtopkssgd_tpu.modes (single vocabulary, no drift).
from gtopkssgd_tpu.modes import (  # noqa: E402  (re-export)
    ALLGATHER_MODES,
    DENSE_MODES,
    GTOPK_MODES,
    HIER_MODES,
    LAYERWISE_MODES,
)


def _is_pow2(p: int) -> bool:
    return p > 0 and (p & (p - 1)) == 0


def gtopk_allreduce(
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
) -> Tuple[Array, Array]:
    """Global top-k sparse allreduce over `axis_name` (hypercube ppermute).

    Input: this device's local top-k set (vals f32[k], idx i32[k], unique
    indices, sentinel = n for padding). Output: the *global* gTop-k set,
    bit-identical on every device along the axis — values are SUMS over
    contributing devices (divide by axis_size for an average).

    Non-power-of-two axis sizes fall back to allgather + global reselect
    (identical result to a flat merge tree; the hypercube needs 2^m ranks —
    the reference handled ragged P with masked sends, which on ICI buys
    nothing over the fallback).
    """
    if not _is_pow2(axis_size):
        return _allgather_reselect(
            vals, idx, k=k, n=n, axis_name=axis_name, axis_size=axis_size
        )
    rounds = int(math.log2(axis_size))
    for r in range(rounds):
        bit = 1 << r
        perm = [(i, i ^ bit) for i in range(axis_size)]
        pvals = lax.ppermute(vals, axis_name, perm)
        pidx = lax.ppermute(idx, axis_name, perm)
        vals, idx = merge_sparse_sets(vals, idx, pvals, pidx, k, n)
    return vals, idx


def _dense_reselect(dense: Array, k: int, n: int) -> Tuple[Array, Array]:
    """Exact top-k over a densified sparse sum, restoring the sentinel
    convention (index n, value 0) on empty slots. Shared tail of both
    allgather-style fallbacks."""
    gvals, gidx = topk_abs(dense, k)
    empty = gvals == 0.0
    gidx = jnp.where(empty, n, gidx).astype(jnp.int32)
    return gvals, gidx


def _allgather_reselect(
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
) -> Tuple[Array, Array]:
    """Gather all P local sets, sparse-sum duplicates, reselect global top-k.

    Used as the ragged-P fallback for gtopk. Duplicate indices across the
    P*k candidates are summed via a dense scatter (exact, not pairwise), then
    reselected.  Comm is O(kP) but result semantics differ from the hypercube
    only in being *exact* top-k of the sparse sum (a superset-quality result).
    """
    all_vals = lax.all_gather(vals, axis_name, tiled=True)  # (P*k,)
    all_idx = lax.all_gather(idx, axis_name, tiled=True)
    return _dense_reselect(scatter_add_dense(n, all_idx, all_vals), k, n)


def ici_dense_psum(x: Array, *, axis_name: str, axis_size: int,
                   ici_size: int) -> Array:
    """Dense allreduce WITHIN each contiguous ICI slice (device r belongs to
    slice r // ici_size — contiguity matters: make_mesh lays ranks out along
    the torus, so a contiguous block of ici_size ranks is ICI-adjacent and
    this traffic rides ICI links only).

    Level 1 of the hierarchical mode: after this, every device of a slice
    holds the identical slice-summed tensor, so the slice behaves as one
    logical gTop-k worker for the cross-slice level.

    Built from `lax.ppermute` rounds because shard_map's psum does not
    support axis_index_groups. Determinism contract: every device of a
    slice must end up with the BITWISE-identical sum — the hierarchical
    mode compresses the result with top-k, which is discontinuous, so a
    1-ulp difference at the k-th magnitude would make slice members select
    different index sets and silently diverge. Recursive doubling gives
    this for free (each round adds two operands that are identical up to
    commutation, and IEEE addition is commutative); for non-power-of-two
    slice sizes the extra offsets are folded into the largest
    power-of-two block first, hypercubed there, and the result broadcast
    back — every device's sum is built with the same association. (A
    rotate-and-accumulate ring would sum in a different order on each
    device: not bitwise safe.)
    """
    if ici_size <= 1:
        return x
    if axis_size % ici_size != 0:
        raise ValueError(
            f"axis size {axis_size} not divisible by ici_size={ici_size}"
        )
    p, s = axis_size, ici_size

    def _hypercube(x, width):
        # recursive doubling among slice offsets [0, width); offsets
        # outside receive zeros and must keep their value via the mask
        r = 1
        j = lax.axis_index(axis_name) % s
        while r < width:
            perm = [
                (i, (i // s) * s + ((i % s) ^ r))
                for i in range(p) if (i % s) < width
            ]
            recv = lax.ppermute(x, axis_name, perm)
            x = jnp.where(j < width, x + recv, x) if width < s else x + recv
            r <<= 1
        return x

    if _is_pow2(s):
        return _hypercube(x, s)
    m = 1 << (s.bit_length() - 1)  # largest power of two <= s
    e = s - m                      # extra offsets [m, s)
    j = lax.axis_index(axis_name) % s
    # fold extras down: offset m+t sends to offset t
    perm = [(i, i - m) for i in range(p) if (i % s) >= m]
    recv = lax.ppermute(x, axis_name, perm)
    x = jnp.where(j < e, x + recv, x)
    x = _hypercube(x, m)
    # broadcast the completed sum back up to the extras
    perm = [(i, i + m) for i in range(p) if (i % s) < e]
    recv = lax.ppermute(x, axis_name, perm)
    return jnp.where(j >= m, recv, x)


def hier_gtopk_allreduce(
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
    ici_size: int,
) -> Tuple[Array, Array]:
    """Cross-slice gTop-k hypercube (level 2 of the hierarchical mode).

    Inputs are per-device local top-k sets that are already identical within
    each slice (computed from the ici_dense_psum'd gradient), so the tree
    only needs to run over the `n_slices = axis_size / ici_size` slice
    index.  Every device participates (SPMD): at round r, device
    `(s, j)` exchanges with `(s XOR 2^r, j)` — i.e. flat-rank partner
    `(s ^ bit) * ici_size + j` — so each intra-slice offset j runs its own
    redundant-but-identical copy of the tree and no device idles.  Non-pow2
    slice counts fall back to a grouped allgather + reselect (exact sparse
    sum over the slice representatives), mirroring gtopk_allreduce's
    ragged-P fallback.
    """
    n_slices = axis_size // ici_size
    if n_slices == 1:
        return vals, idx
    if not _is_pow2(n_slices):
        # Ragged slice count: gather ALL P sets in identical rank order
        # (full all_gather — the grouped variant is unavailable under
        # shard_map), keep one representative row per slice, and
        # scatter-add them in the same canonical slice order on every
        # device before the exact reselect. Every device then runs the
        # identical reduction on identical data -> bitwise-identical
        # result everywhere. (A per-slice ring would fold the dense sum
        # in a different order on each slice: non-associative float adds
        # can differ by ulps, and top-k is discontinuous, so slices could
        # silently select different global sets.) Comm is O(k P), same
        # class as the flat non-pow2 fallback.
        all_vals = lax.all_gather(vals, axis_name)          # [P, k]
        all_idx = lax.all_gather(idx, axis_name)
        rep_vals = all_vals[::ici_size].reshape(-1)         # [n_slices*k]
        rep_idx = all_idx[::ici_size].reshape(-1)
        return _dense_reselect(scatter_add_dense(n, rep_idx, rep_vals), k, n)
    rounds = int(math.log2(n_slices))
    for r in range(rounds):
        bit = 1 << r
        perm = [
            (i, ((i // ici_size) ^ bit) * ici_size + (i % ici_size))
            for i in range(axis_size)
        ]
        pvals = lax.ppermute(vals, axis_name, perm)
        pidx = lax.ppermute(idx, axis_name, perm)
        vals, idx = merge_sparse_sets(vals, idx, pvals, pidx, k, n)
    return vals, idx


def topk_allgather(
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
) -> Array:
    """DGC-style baseline (reference mode 'topk'/'topkA'): allgather every
    device's local top-k and apply the union — no global reselect, so every
    local pick lands and no residual repair is needed. Returns the DENSE
    summed update f32[n] (the union can hold up to k*P distinct indices, so a
    sparse fixed-k return shape does not exist for this mode)."""
    all_vals = lax.all_gather(vals, axis_name, tiled=True)
    all_idx = lax.all_gather(idx, axis_name, tiled=True)
    return scatter_add_dense(n, all_idx, all_vals)


def dense_allreduce(x: Array, *, axis_name: str) -> Array:
    """Dense baseline: one psum over the DP axis (reference MPI.Allreduce)."""
    return lax.psum(x, axis_name)


def sparse_allreduce(
    mode: str,
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
    ici_size: int = 1,
) -> Tuple[Array, Array, bool]:
    """Mode dispatch preserving the reference's L2/L1 boundary.

    Returns (result, gidx, needs_repair):
      * 'gtopk'      -> result = gvals f32[k], gidx = i32[k], True.
      * 'gtopk_hier' -> same shapes; the tree runs over slices only (the
                        caller must have ici_dense_psum'd the gradient
                        BEFORE compression so within-slice sets agree).
      * 'allgather'  -> result = the dense summed update f32[n], gidx = None,
                        False (the union of P local sets has variable size up
                        to k*P, so no fixed-k sparse return shape exists; no
                        repair because every local pick is applied).
    This is the one place the return shape differs across modes; the
    distributed optimizer branches on `gidx is None`.
    """
    if mode in GTOPK_MODES or mode in LAYERWISE_MODES:
        # Layer-wise mode changes only the LOCAL selection (per-layer k_l
        # instead of one global top-k); the wire protocol is the same
        # fixed-K (vals, idx) set, so the hypercube runs unchanged.
        gvals, gidx = gtopk_allreduce(
            vals, idx, k=k, n=n, axis_name=axis_name, axis_size=axis_size
        )
        return gvals, gidx, True
    if mode in HIER_MODES:
        gvals, gidx = hier_gtopk_allreduce(
            vals, idx, k=k, n=n, axis_name=axis_name, axis_size=axis_size,
            ici_size=ici_size,
        )
        return gvals, gidx, True
    if mode in ALLGATHER_MODES:
        dense = topk_allgather(
            vals, idx, k=k, n=n, axis_name=axis_name, axis_size=axis_size
        )
        return dense, None, False
    raise ValueError(f"unknown sparse allreduce mode {mode!r}")


def comm_bytes_per_step(mode: str, n: int, k: int, p: int,
                        ici_size: int = 1) -> int:
    """Per-device communication volume model (paper §3 complexity table):
    gtopk O(k log P), allgather O(k P), dense O(N). 8 bytes per (f32, i32)
    element pair; dense counts 4-byte f32 once per element (ring allreduce
    moves ~2N elements, we report the N model like the paper).

    'gtopk_hier' reports the two levels summed: a dense O(N) within the
    slice (which rides ICI — fast links, usually not the bottleneck the
    model is meant to expose) plus the sparse O(k log(P/ici)) across
    slices (the DCN hop the hierarchy exists to thin out)."""
    if mode in GTOPK_MODES or mode in LAYERWISE_MODES:
        # layerwise: same wire protocol, K differs from rho*N only by the
        # +1-per-tiny-layer rounding of k_l = ceil(rho * n_l).
        if not _is_pow2(p):
            return 8 * k * p
        return 8 * k * max(1, int(math.log2(p)))
    if mode in HIER_MODES:
        n_slices = max(1, p // max(1, ici_size))
        sparse = (8 * k * int(math.log2(n_slices)) if _is_pow2(n_slices)
                  else 8 * k * p)  # ragged: full all_gather fallback
        dense = 4 * n if ici_size > 1 else 0
        return dense + sparse
    if mode in ALLGATHER_MODES:
        return 8 * k * p
    if mode in DENSE_MODES:
        return 4 * n
    raise ValueError(f"unknown mode {mode!r}")
