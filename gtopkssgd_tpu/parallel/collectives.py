"""The three reduction modes of the reference allreducer, TPU-native.

Reference parity (allreducer.py in hclhkbu/gtopkssgd, SURVEY.md C5): three
modes behind one interface —

  (a) gTop-k tree  — log2(P) rounds of pairwise exchange of concatenated
      [values; indices] buffers, merge-then-reselect each round, then a
      reverse-tree broadcast (paper Algorithm 2).  O(k log P) per rank.
  (b) top-k allgather (DGC baseline)               O(k P) per rank.
  (c) dense allreduce                               O(N).

TPU redesign notes:

  * The reference tree is asymmetric (half the ranks go idle each round and
    rank 0 re-broadcasts down the tree — 2 log2 P total rounds).  SPMD wants
    symmetry, so we use the recursive-doubling (hypercube) formulation: at
    round r every device exchanges with `rank XOR 2^r` via `lax.ppermute` and
    both partners compute the identical merged top-k.  After log2(P) rounds
    every device holds the same global set — the reverse broadcast vanishes
    and total rounds HALVE vs the reference.  Equivalence: the merge
    (sparse-sum + reselect) is commutative and order-canonical
    (ops.topk.merge_sparse_sets), proven against a numpy oracle in
    tests/test_collectives.py.

  * All functions here run INSIDE a `jax.shard_map` body over the `dp` mesh
    axis — they are per-device views with collectives over `axis_name`.

  * gTop-k semantics (same as reference): the result is top-k of the
    *hierarchically merged partial sums*, which is not always exactly the
    top-k of the full dense sum — that approximation is the algorithm, and
    error feedback compensates (arXiv:1911.08772 analyzes why this
    converges).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from gtopkssgd_tpu.ops import merge_sparse_sets, scatter_add_dense
from gtopkssgd_tpu.parallel.codec import get_codec

Array = jax.Array

# Re-exported for callers that reach collectives directly; the canonical
# definition lives in gtopkssgd_tpu.modes (single vocabulary, no drift).
from gtopkssgd_tpu.modes import (  # noqa: E402  (re-export)
    ALLGATHER_MODES,
    DENSE_MODES,
    GTOPK_MODES,
    HIER_MODES,
    LAYERWISE_MODES,
)


def _is_pow2(p: int) -> bool:
    return p > 0 and (p & (p - 1)) == 0


def gtopk_allreduce(
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
    codec="fp32",
) -> Tuple[Array, Array]:
    """Global top-k sparse allreduce over `axis_name` (hypercube ppermute).

    Input: this device's local top-k set (vals f32[k], idx i32[k], unique
    indices, sentinel = n for padding). Output: the *global* gTop-k set,
    bit-identical on every device along the axis — values are SUMS over
    contributing devices (divide by axis_size for an average).

    Non-power-of-two axis sizes run the SAME tree with masked folds
    (reference parity: the MPI allreducer handled ragged P with masked
    sends inside its tree — SURVEY.md C5): the e = P - 2^m extra ranks
    fold their sets into ranks [0, e) first, the hypercube runs over the
    2^m power-of-two block, and the finished global set is sent back up
    to the extras. log2(m) + 2 rounds of O(k) traffic — O(k log P), vs
    the O(kP) allgather fallback this replaces (round-4 verdict missing
    #5: the fallback surrendered the tree exactly where the DCN model
    says it matters, at small possibly-ragged slice counts).
    """
    part_ranks = [[i] for i in range(axis_size)]
    return _merge_tree(vals, idx, k=k, n=n, axis_name=axis_name,
                       part_ranks=part_ranks,
                       my_part=lax.axis_index(axis_name), codec=codec)


def tree_rounds(q: int) -> int:
    """Exchange rounds of the (masked) merge tree over q participants:
    log2(q) at powers of two; ragged q pays fold + unfold around the
    2^floor(log2 q) block's hypercube. Shared by comm_bytes_per_step and
    benchmarks/scaling_model.py so the comm model cannot drift from the
    implemented tree shape."""
    if q <= 1:
        return 0
    if _is_pow2(q):
        return int(math.log2(q))
    return (q.bit_length() - 1) + 2


def _merge_tree(vals, idx, *, k, n, axis_name, part_ranks, my_part,
                codec="fp32"):
    """Masked-hypercube merge-then-reselect over `q = len(part_ranks)`
    LOGICAL participants (the one tree under every gtopk variant: flat
    pow2, flat ragged, hierarchical cross-slice, hierarchical ragged).

    ``part_ranks[a]`` lists the flat mesh ranks that hold participant a's
    set — every list the same length; each of those ranks runs its own
    redundant-but-identical copy of the tree so no device idles (SPMD).
    ``my_part`` is this device's traced participant id. Precondition:
    ranks of one participant hold BITWISE-identical (vals, idx) — trivial
    for flat modes (one rank per participant); the hier caller gets it
    from ici_dense_psum's determinism contract.

    Non-power-of-two q runs the SAME tree with masked folds (reference
    parity: the MPI allreducer handled ragged P with masked sends inside
    its tree — SURVEY.md C5), e = q - 2^m extras folding in first and
    adopting the finished set at the end: tree_rounds(q) rounds of O(k)
    traffic, vs the O(kq) allgather fallback this replaced in round 5
    (round-4 verdict missing #5).

    Determinism: every round's merge is order-canonical
    (ops.topk.merge_sparse_sets) and the pair tree has the same shape on
    every rank, so all participants [0, m) finish bitwise identical and
    the extras adopt that agreed set verbatim. Semantics: the result is
    the top-k of HIERARCHICALLY merged partial sums — not always the
    exact top-k of the full sparse sum; that approximation is the gTop-k
    algorithm itself, and error feedback absorbs it
    (compression.TopKCompressor.repair docstring).

    Wire codec (parallel.codec): every round ships
    ``codec.encode(vals, idx)`` instead of the raw pair and each side
    merges DECODED sets — its own wire's decode against the partner's.
    Because encode is deterministic, decode(own wire) on rank A is
    bit-identical to what A's partner decodes, so both partners merge
    the same pair of dequantized sets and the bitwise-agreement
    invariant above survives quantization unchanged. The fp32 codec's
    encode/decode are identity, reproducing the pre-codec tree
    bit-for-bit. The unfold round requantizes on BOTH sides (extras
    adopt the decoded wire, finished participants adopt their own
    wire's decode) so all q participants still end bit-identical.
    """
    q = len(part_ranks)
    codec = get_codec(codec)
    if q == 1:
        return vals, idx
    m = 1 << (q.bit_length() - 1)  # largest power of two <= q
    e = q - m                      # extra participants [m, q)

    def ship(vals, idx, perm):
        """Encode -> ppermute every wire buffer -> decode both ends."""
        wire = codec.encode(vals, idx, n=n)
        pwire = tuple(lax.ppermute(w, axis_name, perm) for w in wire)
        return codec.decode(wire, k=k, n=n), codec.decode(pwire, k=k, n=n)

    def exchange(vals, idx, pairs, receives):
        """One ppermute round over participant `pairs` + merge. `receives`
        is a traced per-device bool — None when every device receives.
        Non-receivers get ppermute's zero-fill (which a quantized codec
        decodes to garbage); index 0 repeated k times would break the
        merge's duplicates-come-in-pairs rule, so their received set is
        turned into pure sentinel padding (merge no-op) AFTER decode.
        """
        perm = [(s, d) for a, b in pairs
                for s, d in zip(part_ranks[a], part_ranks[b])]
        (dvals, didx), (pvals, pidx) = ship(vals, idx, perm)
        if receives is not None:
            pvals = jnp.where(receives, pvals, 0.0)
            pidx = jnp.where(receives, pidx, n)
        return merge_sparse_sets(dvals, didx, pvals, pidx, k, n)

    if e:
        # fold: extra m+t sends its set down to participant t (t < e)
        vals, idx = exchange(vals, idx,
                             [(m + t, t) for t in range(e)], my_part < e)
    for r in range(int(math.log2(m))):
        bit = 1 << r
        vals, idx = exchange(vals, idx,
                             [(a, a ^ bit) for a in range(m)],
                             my_part < m if e else None)
    if e:
        # unfold: extras ADOPT (not merge) the finished global set —
        # through the codec, so extras and finished participants both
        # hold decode(encode(final set)) and stay bit-identical.
        perm = [(s, d) for t in range(e)
                for s, d in zip(part_ranks[t], part_ranks[m + t])]
        (dvals, didx), (pvals, pidx) = ship(vals, idx, perm)
        extra = my_part >= m
        vals = jnp.where(extra, pvals, dvals)
        idx = jnp.where(extra, pidx, didx)
    return vals, idx


def ici_dense_psum(x: Array, *, axis_name: str, axis_size: int,
                   ici_size: int) -> Array:
    """Dense allreduce WITHIN each contiguous ICI slice (device r belongs to
    slice r // ici_size — contiguity matters: make_mesh lays ranks out along
    the torus, so a contiguous block of ici_size ranks is ICI-adjacent and
    this traffic rides ICI links only).

    Level 1 of the hierarchical mode: after this, every device of a slice
    holds the identical slice-summed tensor, so the slice behaves as one
    logical gTop-k worker for the cross-slice level.

    Built from `lax.ppermute` rounds because shard_map's psum does not
    support axis_index_groups. Determinism contract: every device of a
    slice must end up with the BITWISE-identical sum — the hierarchical
    mode compresses the result with top-k, which is discontinuous, so a
    1-ulp difference at the k-th magnitude would make slice members select
    different index sets and silently diverge. Recursive doubling gives
    this for free (each round adds two operands that are identical up to
    commutation, and IEEE addition is commutative); for non-power-of-two
    slice sizes the extra offsets are folded into the largest
    power-of-two block first, hypercubed there, and the result broadcast
    back — every device's sum is built with the same association. (A
    rotate-and-accumulate ring would sum in a different order on each
    device: not bitwise safe.)
    """
    if ici_size <= 1:
        return x
    if axis_size % ici_size != 0:
        raise ValueError(
            f"axis size {axis_size} not divisible by ici_size={ici_size}"
        )
    p, s = axis_size, ici_size

    def _hypercube(x, width):
        # recursive doubling among slice offsets [0, width); offsets
        # outside receive zeros and must keep their value via the mask
        r = 1
        j = lax.axis_index(axis_name) % s
        while r < width:
            perm = [
                (i, (i // s) * s + ((i % s) ^ r))
                for i in range(p) if (i % s) < width
            ]
            recv = lax.ppermute(x, axis_name, perm)
            x = jnp.where(j < width, x + recv, x) if width < s else x + recv
            r <<= 1
        return x

    if _is_pow2(s):
        return _hypercube(x, s)
    m = 1 << (s.bit_length() - 1)  # largest power of two <= s
    e = s - m                      # extra offsets [m, s)
    j = lax.axis_index(axis_name) % s
    # fold extras down: offset m+t sends to offset t
    perm = [(i, i - m) for i in range(p) if (i % s) >= m]
    recv = lax.ppermute(x, axis_name, perm)
    x = jnp.where(j < e, x + recv, x)
    x = _hypercube(x, m)
    # broadcast the completed sum back up to the extras
    perm = [(i, i + m) for i in range(p) if (i % s) < e]
    recv = lax.ppermute(x, axis_name, perm)
    return jnp.where(j >= m, recv, x)


def hier_gtopk_allreduce(
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
    ici_size: int,
    codec="fp32",
) -> Tuple[Array, Array]:
    """Cross-slice gTop-k hypercube (level 2 of the hierarchical mode).

    Inputs are per-device local top-k sets that are already identical within
    each slice (computed from the ici_dense_psum'd gradient — that is the
    _merge_tree precondition), so the tree runs over the
    `n_slices = axis_size / ici_size` slice index: participant s's ranks
    are the ici_size devices of slice s, each running its own
    redundant-but-identical copy of the tree so no device idles. Ragged
    slice counts take the same masked tree (fold/unfold) as the flat
    mode — O(k log n_slices) across DCN, where before round 5 they fell
    back to an O(kP) all_gather.
    """
    n_slices = axis_size // ici_size
    if n_slices == 1:
        return vals, idx
    part_ranks = [
        [s * ici_size + j for j in range(ici_size)]
        for s in range(n_slices)
    ]
    return _merge_tree(vals, idx, k=k, n=n, axis_name=axis_name,
                       part_ranks=part_ranks,
                       my_part=lax.axis_index(axis_name) // ici_size,
                       codec=codec)


def balanced_cap(k: int, p: int, n: int) -> int:
    """Per-destination wire capacity of the balanced schedule.

    Each rank ships at most `cap` picks to each owner rank per step. A
    perfectly uniform index distribution lands k/p picks per owner; the
    3/2 slack absorbs typical skew without giving back the O(k) volume
    win (p ranks x cap stays ~1.5k vs the tree's k*log2(p)). Clamped to
    k (a rank never holds more than k picks total) and to the owner's
    chunk ceil(n/p) (a range cannot receive more distinct indices than
    it has slots — this also guarantees the owner-side top_k is legal).
    Picks beyond cap simply never reach their owner; the optimizer's
    error-feedback repair restores them exactly, same as tree rejects.
    """
    cap = -(-3 * k // (2 * p))
    return max(1, min(cap, k, -(-n // p)))


def balanced_gtopk_allreduce(
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
    codec="fp32",
) -> Tuple[Array, Array]:
    """Ok-Topk-style balanced split-and-reduce sparse allreduce
    (arXiv:2201.07598) — the O(k) alternative to the O(k log P) tree.

    Rank r OWNS the contiguous index range [r*chunk, (r+1)*chunk) with
    chunk = ceil(n/p). Three phases:

      1. scatter: p-1 ppermute rounds; in round s every rank ships to
         rank (r+s) mod p the <= cap largest-|value| of its picks whose
         indices land in the destination's range (cap = balanced_cap;
         sets are chunk-balanced through the same codec wire framing as
         the tree, so each round moves one cap-of-n encoded set).
         Own-range picks are applied locally without touching the wire.
      2. reduce: each owner scatter-adds received picks into a dense
         f32[chunk] accumulator for its range and keeps the top-cap of
         |sum| as its merged owner set (zero slots -> sentinel n).
      3. allgather: every rank gathers all p codec-encoded owner sets
         and reselects the global top-k from the p*cap candidates.

    Determinism: phase-3 input is the identical all_gather output on
    every rank and owner ranges are disjoint (no cross-rank duplicate
    indices to merge), so one shared top_k reselect makes all ranks
    bit-identical — no broadcast round needed. Overflow (capped-out
    picks) and global-reselect rejects both leave the pick's index out
    of the returned gidx, so the existing error-feedback repair
    (compression.TopKCompressor.repair) restores them exactly; no new
    repair machinery. Like the tree, the result approximates the dense
    top-k (a low local |value| can be capped out even if globally
    large); error feedback absorbs the difference.
    """
    p = axis_size
    codec = get_codec(codec)
    if p == 1:
        return vals, idx
    chunk = -(-n // p)
    cap = balanced_cap(k, p, n)
    r = lax.axis_index(axis_name)
    off = r * chunk
    real = idx < n
    owner = jnp.minimum(idx // chunk, p - 1)

    def accumulate(acc, pvals, pidx):
        """Scatter decoded picks into this rank's owned chunk. Indices
        outside [off, off+chunk) — including the sentinel n, which CAN
        alias into the last rank's slot arithmetic when n < chunk*p —
        are parked at the dropped slot `chunk` explicitly."""
        loc = pidx - off
        ok = (pidx < n) & (loc >= 0) & (loc < chunk)
        return acc.at[jnp.where(ok, loc, chunk)].add(
            jnp.where(ok, pvals, 0.0), mode="drop")

    # phase 1+2: own picks land directly; remote picks ride the wire.
    acc = accumulate(jnp.zeros((chunk,), jnp.float32),
                     jnp.where(real & (owner == r), vals, 0.0), idx)
    for s in range(1, p):
        dest = (r + s) % p
        dmask = real & (owner == dest)
        mag = jnp.where(dmask, jnp.abs(vals), -1.0)
        _, pos = lax.top_k(mag, cap)
        sel = jnp.take(mag, pos) >= 0.0
        svals = jnp.where(sel, jnp.take(vals, pos), 0.0)
        sidx = jnp.where(sel, jnp.take(idx, pos), n).astype(jnp.int32)
        wire = codec.encode(svals, sidx, n=n)
        perm = [(i, (i + s) % p) for i in range(p)]
        pwire = tuple(lax.ppermute(w, axis_name, perm) for w in wire)
        pvals, pidx = codec.decode(pwire, k=cap, n=n)
        acc = accumulate(acc, pvals, pidx)

    # owner set: top-cap of the reduced range (cap <= chunk by clamp).
    osel_mag, osel_pos = lax.top_k(jnp.abs(acc), cap)
    keep = osel_mag > 0.0
    ovals = jnp.where(keep, jnp.take(acc, osel_pos), 0.0)
    ogidx = jnp.where(keep, osel_pos + off, n).astype(jnp.int32)

    # phase 3: gather encoded owner sets, shared global reselect.
    gwire = codec.encode(ovals, ogidx, n=n)
    all_wire = tuple(lax.all_gather(w, axis_name, tiled=False)
                     for w in gwire)  # each [P, ...]
    parts = [codec.decode(tuple(w[t] for w in all_wire), k=cap, n=n)
             for t in range(p)]
    cvals = jnp.concatenate([v for v, _ in parts])
    cidx = jnp.concatenate([i for _, i in parts])
    fmag = jnp.where(cidx < n, jnp.abs(cvals), -1.0)
    _, fpos = lax.top_k(fmag, k)
    fkeep = jnp.take(fmag, fpos) > 0.0
    gvals = jnp.where(fkeep, jnp.take(cvals, fpos), 0.0)
    gidx = jnp.where(fkeep, jnp.take(cidx, fpos), n).astype(jnp.int32)
    return gvals, gidx


def topk_allgather(
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
    codec="fp32",
) -> Array:
    """DGC-style baseline (reference mode 'topk'/'topkA'): allgather every
    device's local top-k and apply the union — no global reselect, so every
    local pick lands and no residual repair is needed. Returns the DENSE
    summed update f32[n] (the union can hold up to k*P distinct indices, so a
    sparse fixed-k return shape does not exist for this mode).

    Every codec takes the same path: encode the local set into wire
    buffers, gather each buffer across the axis, decode all P rank
    slices locally. Decode is deterministic, so the scattered union
    stays bit-identical across devices — and the fp32 codec's
    encode/decode are identities, so for the non-lossy default this
    lowers to exactly the historical raw (vals, idx) gather while
    keeping the exchange on the audited ``codec.encode`` path (the
    codec-wire lint invariant: no sparse payload crosses the wire
    unencoded)."""
    codec = get_codec(codec)
    wire = codec.encode(vals, idx, n=n)
    all_wire = tuple(lax.all_gather(w, axis_name, tiled=False)
                     for w in wire)  # each [P, ...]
    parts = [codec.decode(tuple(w[r] for w in all_wire), k=k, n=n)
             for r in range(axis_size)]
    all_vals = jnp.concatenate([v for v, _ in parts])
    all_idx = jnp.concatenate([i for _, i in parts])
    return scatter_add_dense(n, all_idx, all_vals)


def dense_allreduce(x: Array, *, axis_name: str) -> Array:
    """Dense baseline: one psum over the DP axis (reference MPI.Allreduce)."""
    return lax.psum(x, axis_name)


def sparse_allreduce(
    mode: str,
    vals: Array,
    idx: Array,
    *,
    k: int,
    n: int,
    axis_name: str,
    axis_size: int,
    ici_size: int = 1,
    codec="fp32",
    plan=None,
) -> Tuple[Array, Array, bool]:
    """Mode dispatch preserving the reference's L2/L1 boundary.

    Returns (result, gidx, needs_repair):
      * 'gtopk'      -> result = gvals f32[k], gidx = i32[k], True.
      * 'gtopk_hier' -> same shapes; the tree runs over slices only (the
                        caller must have ici_dense_psum'd the gradient
                        BEFORE compression so within-slice sets agree).
      * 'allgather'  -> result = the dense summed update f32[n], gidx = None,
                        False (the union of P local sets has variable size up
                        to k*P, so no fixed-k sparse return shape exists; no
                        repair because every local pick is applied).
    This is the one place the return shape differs across modes; the
    distributed optimizer branches on `gidx is None`.

    ``plan`` selects the WIRE SCHEDULE within the mode's semantics: a
    parallel.planner.CommPlan (duck-typed — anything with a .schedule
    attribute), a bare schedule name, or None/'auto' for the mode's
    historical default. Only the gtopk family has a real choice today:
    'tree' (hypercube, the default) vs 'balanced' (Ok-Topk split-and-
    reduce). Both return the repair contract (needs_repair=True), so
    the optimizer's error feedback is schedule-agnostic.
    """
    schedule = getattr(plan, "schedule", plan)
    if mode in GTOPK_MODES or mode in LAYERWISE_MODES:
        if schedule not in (None, "auto", "tree", "balanced"):
            raise ValueError(
                f"mode {mode!r} supports schedules 'tree'/'balanced', "
                f"got {schedule!r}")
        if schedule == "balanced":
            gvals, gidx = balanced_gtopk_allreduce(
                vals, idx, k=k, n=n, axis_name=axis_name,
                axis_size=axis_size, codec=codec,
            )
            return gvals, gidx, True
        # Layer-wise mode changes only the LOCAL selection (per-layer k_l
        # instead of one global top-k); the wire protocol is the same
        # fixed-K (vals, idx) set, so the hypercube runs unchanged.
        gvals, gidx = gtopk_allreduce(
            vals, idx, k=k, n=n, axis_name=axis_name, axis_size=axis_size,
            codec=codec,
        )
        return gvals, gidx, True
    if mode in HIER_MODES:
        gvals, gidx = hier_gtopk_allreduce(
            vals, idx, k=k, n=n, axis_name=axis_name, axis_size=axis_size,
            ici_size=ici_size, codec=codec,
        )
        return gvals, gidx, True
    if mode in ALLGATHER_MODES:
        dense = topk_allgather(
            vals, idx, k=k, n=n, axis_name=axis_name, axis_size=axis_size,
            codec=codec,
        )
        return dense, None, False
    raise ValueError(f"unknown sparse allreduce mode {mode!r}")


def comm_bytes_per_step(mode: str, n: int, k: int, p: int,
                        ici_size: int = 1, codec="fp32",
                        schedule=None) -> int:
    """Per-device communication volume model (paper §3 complexity table):
    gtopk O(k log P), allgather O(k P), dense O(N). Each sparse round
    ships one codec-encoded k-of-n set (``codec.wire_set_bytes`` —
    parallel.codec; the fp32 default is the historical 8 bytes per
    (f32, i32) element pair); dense counts 4-byte f32 once per element
    (ring allreduce moves ~2N elements, we report the N model like the
    paper).

    'gtopk_hier' reports the two levels summed: a dense O(N) within the
    slice (which rides ICI — fast links, usually not the bottleneck the
    model is meant to expose, and always fp32: the codec applies to the
    sparse set only) plus the sparse O(k log(P/ici)) across slices (the
    DCN hop the hierarchy exists to thin out).

    ``schedule`` mirrors sparse_allreduce's plan dispatch: for the gtopk
    family, 'balanced' models the Ok-Topk schedule — p-1 scatter rounds
    plus a p-slice allgather, each moving one cap-of-n encoded set —
    while None/'auto'/'tree' keep the historical tree model. The two
    formulas share balanced_cap/tree_rounds with the implementation, so
    the ledger audit measures exactly what the wire ships."""
    set_bytes = get_codec(codec).wire_set_bytes(k, n)
    if mode in GTOPK_MODES or mode in LAYERWISE_MODES:
        if schedule == "balanced":
            cap_bytes = get_codec(codec).wire_set_bytes(
                balanced_cap(k, p, n), n)
            return cap_bytes * max(1, 2 * p - 1)
        # layerwise: same wire protocol, K differs from rho*N only by the
        # +1-per-tiny-layer rounding of k_l = ceil(rho * n_l).
        return set_bytes * max(1, tree_rounds(p))
    if mode in HIER_MODES:
        n_slices = max(1, p // max(1, ici_size))
        sparse = set_bytes * tree_rounds(n_slices)
        dense = 4 * n if ici_size > 1 else 0
        return dense + sparse
    if mode in ALLGATHER_MODES:
        return set_bytes * p
    if mode in DENSE_MODES:
        return 4 * n
    raise ValueError(f"unknown mode {mode!r}")
