"""Sparse collective communication over the TPU mesh (reference layer L1).

TPU-native replacement for allreducer.py::AllReducer in hclhkbu/gtopkssgd,
which ran mpi4py Send/Recv/Allgather/Allreduce on host-side numpy staging
buffers from a background thread. Here every collective is an XLA op on
HBM-resident arrays inside one jitted SPMD program: `lax.ppermute` pair
exchanges ride ICI for the gTop-k tree, `all_gather` implements the DGC
baseline, `psum` the dense baseline. No threads, no host staging, no D2H/H2D.
"""

from gtopkssgd_tpu.parallel.bucketing import (
    BUCKETS_DEFAULT,
    BucketPlan,
    buckets_key,
    parse_buckets,
    plan_buckets,
)
from gtopkssgd_tpu.parallel.codec import (
    CODEC_NAMES,
    WireCodec,
    get_codec,
    roundtrip_aligned,
)
from gtopkssgd_tpu.parallel.collectives import (
    balanced_cap,
    balanced_gtopk_allreduce,
    dense_allreduce,
    gtopk_allreduce,
    hier_gtopk_allreduce,
    ici_dense_psum,
    topk_allgather,
    sparse_allreduce,
    comm_bytes_per_step,
    tree_rounds,
)
from gtopkssgd_tpu.parallel.mesh import make_mesh, dp_axis
from gtopkssgd_tpu.parallel.planner import (
    CommPlan,
    PlanDecision,
    build_decision,
    candidate_plans,
    resolve_plan,
    validate_pin,
)

__all__ = [
    "BUCKETS_DEFAULT",
    "BucketPlan",
    "buckets_key",
    "parse_buckets",
    "plan_buckets",
    "CODEC_NAMES",
    "WireCodec",
    "get_codec",
    "roundtrip_aligned",
    "balanced_cap",
    "balanced_gtopk_allreduce",
    "dense_allreduce",
    "gtopk_allreduce",
    "hier_gtopk_allreduce",
    "ici_dense_psum",
    "topk_allgather",
    "sparse_allreduce",
    "comm_bytes_per_step",
    "tree_rounds",
    "make_mesh",
    "dp_axis",
    "CommPlan",
    "PlanDecision",
    "build_decision",
    "candidate_plans",
    "resolve_plan",
    "validate_pin",
]
