"""Pluggable wire codecs for the sparse (vals, idx) exchange set.

Every sparse collective in this package ships the same payload: a fixed-k
set of (f32 value, i32 index) pairs with sentinel ``idx == n`` padding.
The hypercube merge exchanges that set once per tree round over DCN, so
its on-wire size IS the gTop-k byte bill (ROADMAP item 2). This module
turns the payload into a pluggable codec:

  fp32          identity — (vals, idx) ship as-is, 8 bytes/element.
                Bit-exact with the pre-codec wire (the default).
  int8[:B]      per-block symmetric int8 value quantization (EQuARX
                lineage, arXiv:2506.17615): blocks of B values share one
                max-|v|/127 scale shipped as bf16; indices are
                sort + delta + bitpack coded (below).
  fp8[:B]       same framing with float8_e4m3fn values (max-|v|/448
                block scales) — more dynamic range per element at the
                same 8 bits.

Index coding (quantized codecs): the set is sorted by index (the merge
is order-canonical, so reordering is free), and each index splits into
``l = floor(log2((n+1)/k))`` low bits, bitpacked at exactly l bits per
element, plus a high part whose sorted DELTAS are unary-coded into a
monotone bit-vector (the Elias-Fano refinement of delta coding: the
packed width stays ~log2(n/k) + 2 bits/element instead of the
ceil(log2 n) a flat delta pack would pay, which is what makes the >=3x
DCN reduction reachable at rho=0.001 — a flat pack's 19 index bits at
ResNet-20 scale caps the whole codec at ~2.3x). Everything is padded to
the 32-bit lane: the wire is ONE uint32 buffer of statically-known
length, assembled with ``lax.bitcast_convert_type`` dtype punning —
fixed shapes, jit/ppermute-compatible.

Determinism contract: encode is a pure deterministic function of the
set, so two hypercube partners that decode the same buffer — or a rank
that decodes its OWN buffer — recover bit-identical (vals, idx). The
merge tree exploits this by merging decode(own wire) with decode(partner
wire): both partners see the same pair of dequantized sets and stay
bit-identical through every round (collectives._merge_tree docstring).

Error accounting: the first quantization's error (v - dequant(quant(v)))
is folded into the error-feedback residual at the compression layer
(``roundtrip_aligned`` + compression.TopKCompressor.fold_wire_error), so
convergence self-corrects exactly like top-k truncation error does.
Re-quantization of intermediate merged sums inside the tree is NOT
residual-fed (both partners requantize identically, so it cancels to a
shared, second-order perturbation of the merge oracle).

Byte accounting (``wire_set_bytes``) is host-side integer arithmetic on
the same layout the encoder emits — comm_bytes_per_step, the scaling
model, and the obs ledger all read it, so modeled and shipped bytes
cannot drift.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

_LANE_BITS = 32  # wire lane width: everything pads to whole uint32 words


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


@dataclasses.dataclass(frozen=True)
class _Layout:
    """Static wire layout for one (k, n, block) shape — the single source
    both the encoder and the byte model read."""
    k: int
    n: int
    block: int
    l_bits: int        # low bits per index (Elias-Fano split)
    n_blocks: int      # value scale blocks
    val_words: int     # packed 8-bit values
    scale_words: int   # bf16 block scales
    up_words: int      # monotone high-part bit-vector
    low_words: int     # bitpacked low index bits

    @property
    def total_words(self) -> int:
        return (self.val_words + self.scale_words
                + self.up_words + self.low_words)


def _layout(k: int, n: int, block: int) -> _Layout:
    if k < 1 or n < 1:
        raise ValueError(f"codec layout needs k >= 1, n >= 1 (k={k} n={n})")
    # Index universe is [0, n] — the sentinel n must encode exactly.
    u = n + 1
    l_bits = max(0, (u // k).bit_length() - 1) if u > k else 0
    l_bits = min(l_bits, 31)
    n_blocks = _ceil_div(k, block)
    up_bits = (n >> l_bits) + k  # positions high_i + i, strictly increasing
    return _Layout(
        k=k, n=n, block=block, l_bits=l_bits, n_blocks=n_blocks,
        val_words=_ceil_div(k, 4),
        scale_words=_ceil_div(n_blocks, 2),
        up_words=_ceil_div(up_bits, _LANE_BITS),
        low_words=_ceil_div(k * l_bits, _LANE_BITS),
    )


# --------------------------------------------------------------------------
# Bit plumbing: fixed-width pack/unpack over uint32 lanes. Each element's
# bits may straddle two words; contributions within a word occupy disjoint
# bit ranges, so scatter-add is scatter-or.
# --------------------------------------------------------------------------


def _pack_bits(values: Array, width: int, n_words: int) -> Array:
    """Pack uint32[k] values (< 2^width each) at `width` bits/element."""
    if n_words == 0 or width == 0:
        return jnp.zeros((n_words,), jnp.uint32)
    k = values.shape[0]
    start = jnp.arange(k, dtype=jnp.int32) * width
    w = start // _LANE_BITS
    o = (start % _LANE_BITS).astype(jnp.uint32)
    low = jnp.left_shift(values, o)
    spill = (o + width) > _LANE_BITS
    # o > 0 whenever spill (width <= 32), so the shift stays in [1, 31].
    sh = jnp.where(o > 0, _LANE_BITS - o, 1).astype(jnp.uint32)
    high = jnp.where(spill, jnp.right_shift(values, sh), jnp.uint32(0))
    words = jnp.zeros((n_words,), jnp.uint32)
    words = words.at[w].add(low, mode="drop")
    return words.at[w + 1].add(high, mode="drop")


def _unpack_bits(words: Array, width: int, k: int) -> Array:
    """Inverse of _pack_bits -> uint32[k]."""
    if width == 0:
        return jnp.zeros((k,), jnp.uint32)
    start = jnp.arange(k, dtype=jnp.int32) * width
    w = start // _LANE_BITS
    o = (start % _LANE_BITS).astype(jnp.uint32)
    cur = jnp.take(words, w, mode="clip")
    nxt = jnp.take(words, w + 1, mode="clip")
    lo = jnp.right_shift(cur, o)
    spill = (o + width) > _LANE_BITS
    sh = jnp.where(o > 0, _LANE_BITS - o, 1).astype(jnp.uint32)
    hi = jnp.where(spill, jnp.left_shift(nxt, sh), jnp.uint32(0))
    mask = jnp.uint32(0xFFFFFFFF if width >= 32 else (1 << width) - 1)
    return (lo | hi) & mask


def _bytes_to_words(b: Array) -> Array:
    """uint8[4m] -> uint32[m] via bitcast punning."""
    return lax.bitcast_convert_type(b.reshape(-1, 4), jnp.uint32)


def _words_to_bytes(w: Array) -> Array:
    """uint32[m] -> uint8[4m]."""
    return lax.bitcast_convert_type(w, jnp.uint8).reshape(-1)


# --------------------------------------------------------------------------
# Codec descriptors
# --------------------------------------------------------------------------


class WireCodec:
    """fp32 identity codec: the wire IS (vals, idx), bit-exact with the
    pre-codec collectives. Also the base class quantized codecs extend."""

    name = "fp32"
    values_bits = 32
    scale_bits = 0
    block = 0
    lossy = False

    def index_bits(self, k: int, n: int) -> float:
        return 32.0

    def wire_set_bytes(self, k: int, n: int) -> int:
        """On-wire bytes of one k-of-n sparse set. fp32 must reproduce the
        historical 4-byte-value + 4-byte-index formula exactly (test-pinned:
        the comm model, ledger and baselines all assumed 8k)."""
        return 8 * k

    def bit_budget(self, k: int, n: int) -> Dict[str, float]:
        """Per-element bit decomposition for benches/docs."""
        return {"values_bits": float(self.values_bits),
                "index_bits": self.index_bits(k, n),
                "scale_bits": 0.0,
                "total_bits": float(self.values_bits) + self.index_bits(k, n)}

    def encode(self, vals: Array, idx: Array, *, n: int) -> Tuple[Array, ...]:
        return (vals, idx)

    def decode(self, wire: Tuple[Array, ...], *, k: int, n: int
               ) -> Tuple[Array, Array]:
        return wire[0], wire[1]

    def __repr__(self):
        return f"WireCodec({self.name!r})"


class _QuantCodec(WireCodec):
    """Shared framing for the 8-bit value codecs (int8 / fp8)."""

    values_bits = 8
    scale_bits = 16
    lossy = True

    def __init__(self, block: int):
        if block < 4 or block % 4:
            raise ValueError(
                f"codec block size must be a positive multiple of 4, "
                f"got {block}")
        self.block = block
        self.name = f"{self.base_name}:{block}"

    def index_bits(self, k: int, n: int) -> float:
        lo = _layout(k, n, self.block)
        return (lo.up_words + lo.low_words) * _LANE_BITS / k

    def wire_set_bytes(self, k: int, n: int) -> int:
        return 4 * _layout(k, n, self.block).total_words

    def bit_budget(self, k: int, n: int) -> Dict[str, float]:
        lo = _layout(k, n, self.block)
        return {
            "values_bits": lo.val_words * _LANE_BITS / k,
            "index_bits": (lo.up_words + lo.low_words) * _LANE_BITS / k,
            "scale_bits": lo.scale_words * _LANE_BITS / k,
            "total_bits": lo.total_words * _LANE_BITS / k,
        }

    # -- value quantization hooks (per-block, deterministic) --------------

    def _quant(self, blocks: Array, s32: Array) -> Array:
        raise NotImplementedError

    def _dequant(self, qbytes: Array, s32: Array, kb: int) -> Array:
        raise NotImplementedError

    # -- wire assembly -----------------------------------------------------

    def encode(self, vals: Array, idx: Array, *, n: int) -> Tuple[Array, ...]:
        k = vals.shape[0]
        lo = _layout(k, n, self.block)
        # Order-canonical merge => sorting by index is free; sentinels
        # (idx == n, value 0) sort to the tail and encode exactly.
        sidx, svals = lax.sort((idx, vals), num_keys=1)

        # Values: per-block bf16 scales; quantize against the ROUNDED
        # scale (both ends multiply by the same bf16-derived f32).
        kb = lo.n_blocks * self.block
        blocks = jnp.pad(svals, (0, kb - k)).reshape(lo.n_blocks, self.block)
        amax = jnp.max(jnp.abs(blocks), axis=1)
        scale = (amax / self.qmax).astype(jnp.bfloat16)
        s32 = scale.astype(jnp.float32)
        qbytes = self._quant(blocks, s32)  # uint8[n_blocks, block]
        val_w = _bytes_to_words(
            jnp.pad(qbytes.reshape(-1)[:k], (0, lo.val_words * 4 - k)))

        nb2 = lo.scale_words * 2
        scale_w = lax.bitcast_convert_type(
            jnp.pad(scale, (0, nb2 - lo.n_blocks)).reshape(-1, 2),
            jnp.uint32)

        # Indices: Elias-Fano split at l low bits.
        iu = sidx.astype(jnp.uint32)
        l = lo.l_bits
        low_w = _pack_bits(
            iu & jnp.uint32((1 << l) - 1) if l else iu * 0, l, lo.low_words)
        pos = (sidx >> l) + jnp.arange(k, dtype=jnp.int32)
        up = jnp.zeros((lo.up_words,), jnp.uint32).at[pos // _LANE_BITS].add(
            jnp.left_shift(jnp.uint32(1),
                           (pos % _LANE_BITS).astype(jnp.uint32)),
            mode="drop")
        return (jnp.concatenate([val_w, scale_w, up, low_w]),)

    def decode(self, wire: Tuple[Array, ...], *, k: int, n: int
               ) -> Tuple[Array, Array]:
        lo = _layout(k, n, self.block)
        words = wire[0]
        a = lo.val_words
        b = a + lo.scale_words
        c = b + lo.up_words
        val_w, scale_w, up, low_w = words[:a], words[a:b], words[b:c], words[c:]

        scale = lax.bitcast_convert_type(
            scale_w, jnp.bfloat16).reshape(-1)[:lo.n_blocks]
        s32 = scale.astype(jnp.float32)

        kb = lo.n_blocks * self.block
        qbytes = jnp.pad(_words_to_bytes(val_w)[:k], (0, kb - k))
        vals = self._dequant(qbytes, s32, kb)[:k]

        # Exactly k set bits in a valid upper vector; an all-zero buffer
        # (ppermute zero-fill at masked ranks) decodes to garbage the
        # caller masks to sentinels before the merge.
        bits = jnp.right_shift(
            up[:, None], jnp.arange(_LANE_BITS, dtype=jnp.uint32)[None, :]
        ) & jnp.uint32(1)
        (pos,) = jnp.nonzero(bits.reshape(-1), size=k, fill_value=0)
        high = pos.astype(jnp.int32) - jnp.arange(k, dtype=jnp.int32)
        low = _unpack_bits(low_w, lo.l_bits, k).astype(jnp.int32)
        idx = jnp.left_shift(high, lo.l_bits) | low
        return vals, idx


class Int8Codec(_QuantCodec):
    base_name = "int8"
    qmax = 127.0

    def _quant(self, blocks: Array, s32: Array) -> Array:
        denom = jnp.where(s32 > 0, s32, 1.0)[:, None]
        q = jnp.clip(jnp.round(blocks / denom), -127.0, 127.0)
        return lax.bitcast_convert_type(q.astype(jnp.int8), jnp.uint8)

    def _dequant(self, qbytes: Array, s32: Array, kb: int) -> Array:
        q = lax.bitcast_convert_type(qbytes, jnp.int8).astype(jnp.float32)
        return (q.reshape(-1, self.block) * s32[:, None]).reshape(kb)


class Fp8Codec(_QuantCodec):
    base_name = "fp8"
    qmax = 448.0  # float8_e4m3fn max finite

    def _quant(self, blocks: Array, s32: Array) -> Array:
        denom = jnp.where(s32 > 0, s32, 1.0)[:, None]
        q = jnp.clip(blocks / denom, -448.0, 448.0)
        return lax.bitcast_convert_type(
            q.astype(jnp.float8_e4m3fn), jnp.uint8)

    def _dequant(self, qbytes: Array, s32: Array, kb: int) -> Array:
        q = lax.bitcast_convert_type(
            qbytes, jnp.float8_e4m3fn).astype(jnp.float32)
        return (q.reshape(-1, self.block) * s32[:, None]).reshape(kb)


DEFAULT_BLOCK = 64

#: Flag grammar: fp32 | int8[:BLOCK] | fp8[:BLOCK] (BLOCK a multiple of 4;
#: default 64 — 0.25 scale bits/element).
CODEC_NAMES = ("fp32", "int8", "fp8")

_CACHE: Dict[str, WireCodec] = {}


def get_codec(spec) -> WireCodec:
    """Resolve a codec spec — a WireCodec instance passes through; a
    string follows the ``fp32 | int8[:BLOCK] | fp8[:BLOCK]`` grammar."""
    if isinstance(spec, WireCodec):
        return spec
    if spec is None:
        spec = "fp32"
    spec = str(spec)
    if spec in _CACHE:
        return _CACHE[spec]
    base, _, blk = spec.partition(":")
    if base not in CODEC_NAMES or (base == "fp32" and blk):
        raise ValueError(
            f"unknown wire codec {spec!r} (grammar: fp32 | int8[:BLOCK] "
            f"| fp8[:BLOCK])")
    if base == "fp32":
        codec = WireCodec()
    else:
        try:
            block = int(blk) if blk else DEFAULT_BLOCK
        except ValueError:
            raise ValueError(f"bad codec block size in {spec!r}")
        codec = (Int8Codec if base == "int8" else Fp8Codec)(block)
    _CACHE[spec] = codec
    return codec


def roundtrip_aligned(codec, vals: Array, idx: Array, *, n: int) -> Array:
    """dequant(quant(vals)) returned in the ORIGINAL slot order of
    (vals, idx) — what the sender will effectively contribute through the
    wire. The compression layer folds (vals - roundtrip) into the
    error-feedback residual (TopKCompressor.fold_wire_error) and ships the
    roundtripped values, so repair of a globally-rejected pick restores
    the ORIGINAL value exactly: roundtrip (from repair) + error (already
    in the residual). Identity for fp32."""
    codec = get_codec(codec)
    if not codec.lossy:
        return vals
    qvals, _ = codec.decode(codec.encode(vals, idx, n=n),
                            k=vals.shape[0], n=n)
    # decode order is index-sorted; argsort(idx) maps sorted slot j back
    # to original slot perm[j]. Ties are sentinel slots (value 0 both
    # ways), so stable-vs-unstable tie order cannot change values.
    perm = jnp.argsort(idx, stable=True)
    return jnp.zeros_like(vals).at[perm].set(qvals)
