"""Device mesh construction for data-parallel gTop-k S-SGD.

The reference's process topology is "P MPI ranks, one per GPU"
(dist_trainer.py::main: MPI.COMM_WORLD init + rank->GPU bind). The TPU-native
equivalent is one SPMD program over a 1-D `jax.sharding.Mesh` axis `'dp'`
spanning every chip (single host: local devices; multi-host: call
`jax.distributed.initialize()` first and the same code spans the pod slice).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DP_AXIS = "dp"


def dp_axis() -> str:
    return DP_AXIS


def make_mesh(
    num_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = DP_AXIS,
) -> Mesh:
    """1-D data-parallel mesh over `num_devices` (default: all devices).

    Under tests this sees the 8 virtual CPU devices forced by conftest.py;
    on hardware it sees the chips of the slice. ICI layout: a 1-D DP axis
    lets XLA route ppermute pair exchanges over the torus links directly.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))
