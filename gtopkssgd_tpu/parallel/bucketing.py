"""Byte-balanced gradient bucketing for the layerwise path.

The committed DCN probe fit (benchmarks/results/dcn_probe_*.json) puts
alpha — the per-collective latency term — at ~22 ms, three orders of
magnitude above the per-byte term at realistic rho. Any schedule that
issues one sparse merge per leaf therefore pays L alpha terms per step
where a single concatenated merge pays one. This module computes the
partition of the param leaves into B contiguous buckets that minimizes
the alpha-beta merge cost EXACTLY:

    cost(partition) = sum over buckets b of
        rounds(p, schedule) * alpha_ms                 (latency)
        + comm_bytes(n_b, k_b) / beta                  (volume)

where ``k_b = ceil(density * n_b)`` (the per-bucket k split proportional
to leaf sizes — at B=L it reproduces today's per-leaf quotas, at B=1 it
reproduces the flat mode's global k) and ``comm_bytes`` is the SAME
codec-aware model the ledger prices the wire with
(parallel.collectives.comm_bytes_per_step), so the planner cannot drift
from what the step actually ships. The bandwidth term is not constant in
B: a lossy codec's index words shrink with the bucket-local index space
(Elias-Fano high/low split — parallel.codec), so splitting buys index
bits while costing alpha; the DP resolves that trade exactly.

Bucket indices are BUCKET-LOCAL: each bucket's concatenated operand is
its own [n_b] index space, and every bucket runs the unchanged
codec-framed gTop-k merge (tree or balanced) over its own (vals, idx)
set. The optimizer scatters the reduced update and the error-feedback
residual back to leaves through the static bucket offsets.

Spec grammar (``--buckets``):

    concat   historical default: per-leaf selection, ONE concatenated
             merge over the global index space — today's layerwise wire,
             byte-identical, untouched code path. No BucketPlan exists.
    leaf     B = L: per-leaf selection AND one merge per leaf (the
             fully-layerwise end of the axis the DP interpolates).
    <int>    pinned bucket count B; boundaries still DP-optimal.
    auto     the DP chooses boundaries AND B (cost-minimal over all
             contiguous partitions); ties break toward the historical
             per-leaf end (larger B), so `auto` only coarsens when the
             measured alpha actually pays for it.

The partition DP is O(L^2) states x O(L) transitions — microseconds for
real models (L ~ 10^2) and run once at trace time, host-side.

Pipeline axis (``--pipeline``, PR 15): under the historical ``serial``
execution order a step pays sum_b (T_select_b + T_merge_b), and an
extra bucket can only add alpha — which is why the serial DP honestly
collapses `auto` to B=1. Under ``overlap`` bucket b+1's selection runs
while bucket b's merge rounds are in flight, so the exposed span is the
pipelined

    T_select_1 + sum_{j=2..B} max(T_select_j, T_merge_{j-1}) + T_merge_B

(first select is the fill, last merge the drain). The DP cannot
optimize that non-additive span exactly, so under overlap pricing it
minimizes the additive per-stage surrogate sum_b max(T_select_b,
T_merge_b) — the standard software-pipeline relaxation, exact when
stages are balanced — and `pipeline_span_ms` reports the true span for
the chosen partition. Selection is priced linearly
(`select_cost_ms`), so under SERIAL pricing the select term is
partition-independent and the serial DP objective is unchanged from
PR 11.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import List, Optional, Sequence, Tuple

from ..ops import k_for_density
from .collectives import comm_bytes_per_step, tree_rounds

BUCKETS_DEFAULT = "concat"

# Specs that are words, not counts. Anything else must parse as int >= 1.
_WORD_SPECS = ("concat", "leaf", "auto")

PIPELINE_DEFAULT = "serial"

# --pipeline spec grammar: the two execution orders (modes.PIPELINES)
# plus 'auto', which prices both and keeps the cheaper modeled span.
_PIPELINE_SPECS = ("serial", "overlap", "auto")

# Modeled per-element cost of the fused two-stage selection, in ms per
# 1e6 elements. This is a MODELED constant, not a fit: selection is a
# local bitonic/threshold pass whose throughput is device-bound, and
# one ms per Melem sits in the measured band of the fused-variants
# bench (benchmarks/results/fused_variants_*.json) without pretending
# per-device precision. Linearity is the load-bearing property — it
# makes sum_b select_cost_ms(n_b) independent of the partition, so the
# serial DP objective (merge cost only) stays exact.
SELECT_GAMMA_MS_PER_MELEM = 1.0


def parse_pipeline(spec) -> str:
    """Normalize a --pipeline spec: 'serial' | 'overlap' | 'auto'.

    Raises ValueError on anything else — at build time, not inside the
    jitted step."""
    if isinstance(spec, str):
        word = spec.strip().lower()
        if word in _PIPELINE_SPECS:
            return word
    raise ValueError(
        f"invalid --pipeline spec {spec!r}; grammar: serial | overlap | auto")


def select_cost_ms(n_elems: int) -> float:
    """Modeled ms of one bucket's fused two-stage selection (top-k over
    an [n_b] operand). Linear in n_b by design — see
    SELECT_GAMMA_MS_PER_MELEM."""
    return SELECT_GAMMA_MS_PER_MELEM * float(n_elems) / 1e6


def parse_buckets(spec) -> object:
    """Normalize a --buckets spec: 'concat' | 'leaf' | 'auto' | int B.

    Accepts the string grammar (CLI) or a bare int (programmatic).
    Raises ValueError on anything else — at build time, not inside the
    jitted step.
    """
    if isinstance(spec, bool):  # bool is an int subclass; reject explicitly
        raise ValueError(f"invalid --buckets spec {spec!r}")
    if isinstance(spec, int):
        if spec < 1:
            raise ValueError(f"--buckets count must be >= 1, got {spec}")
        return spec
    if isinstance(spec, str):
        word = spec.strip().lower()
        if word in _WORD_SPECS:
            return word
        try:
            count = int(word)
        except ValueError:
            raise ValueError(
                f"invalid --buckets spec {spec!r}; grammar: "
                "concat | leaf | auto | <int B>") from None
        if count < 1:
            raise ValueError(f"--buckets count must be >= 1, got {count}")
        return count
    raise ValueError(f"invalid --buckets spec {spec!r}")


def buckets_key(spec) -> str:
    """Canonical hashable form of a spec ('concat'/'leaf'/'auto'/'b{B}') —
    the planner-cache and CommPlan.bucketing key."""
    parsed = parse_buckets(spec)
    return parsed if isinstance(parsed, str) else f"b{parsed}"


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """An ordered partition of the param leaves into contiguous buckets.

    ``boundaries`` are B+1 cut points in LEAF index space
    (boundaries[0] == 0, boundaries[-1] == L): bucket b covers leaves
    ``boundaries[b]:boundaries[b+1]``. ``leaf_sizes`` is the flat element
    count of every leaf (jax.tree flatten order — the same order the
    layerwise residual tuple uses), ``ks`` the per-bucket wire k.
    """

    boundaries: Tuple[int, ...]
    leaf_sizes: Tuple[int, ...]
    ks: Tuple[int, ...]
    spec: str = "auto"
    # Resolved execution order (modes.PIPELINES) — never the 'auto'
    # spec word; plan_buckets resolves that before constructing a plan.
    pipeline: str = PIPELINE_DEFAULT

    def __post_init__(self):
        L = len(self.leaf_sizes)
        b = self.boundaries
        if (len(b) < 2 or b[0] != 0 or b[-1] != L
                or any(b[i] >= b[i + 1] for i in range(len(b) - 1))):
            raise ValueError(
                f"boundaries {b} is not a partition of {L} leaves")
        if len(self.ks) != len(b) - 1:
            raise ValueError(
                f"{len(self.ks)} ks for {len(b) - 1} buckets")
        if self.pipeline not in ("serial", "overlap"):
            raise ValueError(
                f"BucketPlan.pipeline must be a resolved execution order "
                f"(serial|overlap), got {self.pipeline!r}")

    @property
    def n_buckets(self) -> int:
        return len(self.boundaries) - 1

    @property
    def sizes(self) -> Tuple[int, ...]:
        """Element count n_b of every bucket's concatenated operand."""
        return tuple(
            sum(self.leaf_sizes[lo:hi])
            for lo, hi in zip(self.boundaries, self.boundaries[1:]))

    @property
    def k_total(self) -> int:
        return sum(self.ks)

    def leaf_range(self, b: int) -> Tuple[int, int]:
        """(lo, hi) leaf-index range of bucket b."""
        return self.boundaries[b], self.boundaries[b + 1]

    def pairs(self) -> Tuple[Tuple[int, int], ...]:
        """((n_b, k_b), ...) — the shape every wire-cost model prices."""
        return tuple(zip(self.sizes, self.ks))

    def to_manifest(self) -> dict:
        """Manifest extras stamping the chosen partition into the run
        header (obs.manifest.run_manifest(**extra)); the ledger reads
        these back via _manifest_params to price the bucketed wire."""
        return {
            "buckets": self.spec,
            "bucket_boundaries": list(self.boundaries),
            "bucket_sizes": list(self.sizes),
            "bucket_ks": list(self.ks),
            "pipeline": self.pipeline,
        }

    @staticmethod
    def from_manifest(manifest: dict) -> Optional["BucketPlan"]:
        """Inverse of to_manifest (leaf_sizes are not stamped — the
        manifest partition is reconstructed at bucket granularity, which
        is all any consumer prices). None when the run was not bucketed."""
        bounds = manifest.get("bucket_boundaries")
        sizes = manifest.get("bucket_sizes")
        ks = manifest.get("bucket_ks")
        if not bounds or not sizes or not ks:
            return None
        # Bucket-granular reconstruction: each bucket becomes one "leaf"
        # of its summed size, boundaries renumbered 0..B.
        return BucketPlan(
            boundaries=tuple(range(len(sizes) + 1)),
            leaf_sizes=tuple(int(s) for s in sizes),
            ks=tuple(int(k) for k in ks),
            spec=str(manifest.get("buckets", "auto")),
            pipeline=str(manifest.get("pipeline", PIPELINE_DEFAULT)),
        )


def merge_rounds(p: int, schedule: Optional[str] = None) -> int:
    """Number of latency-bearing exchange rounds of ONE sparse merge —
    the multiplier on alpha. Mirrors comm_bytes_per_step's round
    structure: the tree pays tree_rounds(p) sequential hops, the
    balanced (Ok-Topk) schedule a p-1 scatter phase plus a p-1
    allgather phase (its rounds overlap destinations but are still
    serialized phases on the critical path)."""
    if p <= 1:
        return 0
    if schedule == "balanced":
        return 2 * (p - 1)
    return tree_rounds(p)


def bucket_cost_ms(n_b: int, k_b: int, *, p: int, codec="fp32",
                   schedule: Optional[str] = None,
                   alpha_ms: float, beta_gbps: float,
                   mode: str = "gtopk_layerwise") -> float:
    """Modeled ms of one bucket's merge: rounds * alpha + bytes / beta.

    Bytes come from the same comm_bytes_per_step model the ledger and
    telemetry use (codec- and schedule-aware), so the DP optimizes the
    quantity the ledger will later audit."""
    if p <= 1:
        return 0.0
    wire = comm_bytes_per_step(mode, n_b, k_b, p, codec=codec,
                               schedule=schedule)
    beta_bytes_per_ms = max(float(beta_gbps), 1e-9) * 1e9 / 1e3
    return merge_rounds(p, schedule) * float(alpha_ms) + wire / beta_bytes_per_ms


def stage_cost_ms(n_b: int, k_b: int, *, pipeline: str = PIPELINE_DEFAULT,
                  p: int, codec="fp32", schedule: Optional[str] = None,
                  alpha_ms: float, beta_gbps: float,
                  mode: str = "gtopk_layerwise") -> float:
    """The DP's per-bucket objective term under a given execution order.

    serial: the merge cost alone. Selection is priced linearly
    (select_cost_ms), so sum_b select_cost_ms(n_b) is the same for every
    partition and adding it could never change the argmin — the PR 11
    objective is preserved bit-for-bit.

    overlap: max(T_select_b, T_merge_b) — the additive pipeline
    surrogate. The true pipelined span (pipeline_span_ms) staggers
    select_j against merge_{j-1} and is not additive over buckets; the
    surrogate pairs each bucket's own two stages instead, which equals
    the true span (up to fill/drain) when stages are balanced — the
    standard software-pipeline relaxation that keeps the partition DP
    exact over the surrogate."""
    merge = bucket_cost_ms(n_b, k_b, p=p, codec=codec, schedule=schedule,
                           alpha_ms=alpha_ms, beta_gbps=beta_gbps, mode=mode)
    if pipeline == "overlap":
        return max(select_cost_ms(n_b), merge)
    return merge


def partition_cost_ms(plan: BucketPlan, *, p: int, codec="fp32",
                      schedule: Optional[str] = None,
                      alpha_ms: float, beta_gbps: float,
                      mode: str = "gtopk_layerwise",
                      pipeline: str = PIPELINE_DEFAULT) -> float:
    """Total modeled objective of a partition — additive over buckets,
    which is what makes the DP below exact. Under 'serial' this is the
    summed merge cost (the PR 11 objective); under 'overlap' the summed
    per-stage max (see stage_cost_ms)."""
    return sum(
        stage_cost_ms(n_b, k_b, pipeline=pipeline, p=p, codec=codec,
                      schedule=schedule, alpha_ms=alpha_ms,
                      beta_gbps=beta_gbps, mode=mode)
        for n_b, k_b in plan.pairs())


def pipeline_span_ms(plan: BucketPlan, *, p: int, codec="fp32",
                     schedule: Optional[str] = None, alpha_ms: float,
                     beta_gbps: float, mode: str = "gtopk_layerwise",
                     pipeline: Optional[str] = None) -> float:
    """True modeled wall-clock span of one step's select+merge chain
    under an execution order (defaults to the plan's own).

    serial:  sum_b (T_select_b + T_merge_b) — the paper's sequential sum.
    overlap: T_select_1 + sum_{j=2..B} max(T_select_j, T_merge_{j-1})
             + T_merge_B — select_1 is the pipeline fill (nothing to
             hide it under), merge_B the drain, and every interior step
             exposes whichever of the two concurrent stages is longer.

    This is the quantity `auto` pipeline resolution compares and the
    span `report plan` / merge_bench print; the DP optimizes the
    additive surrogate (stage_cost_ms) instead because this one is not
    additive over buckets."""
    pipe = plan.pipeline if pipeline is None else pipeline
    sel = [select_cost_ms(n_b) for n_b in plan.sizes]
    merge = [
        bucket_cost_ms(n_b, k_b, p=p, codec=codec, schedule=schedule,
                       alpha_ms=alpha_ms, beta_gbps=beta_gbps, mode=mode)
        for n_b, k_b in plan.pairs()]
    if pipe != "overlap":
        return sum(sel) + sum(merge)
    span = sel[0]
    for j in range(1, len(sel)):
        span += max(sel[j], merge[j - 1])
    return span + merge[-1]


def _leaf_boundaries(n_leaves: int) -> Tuple[int, ...]:
    return tuple(range(n_leaves + 1))


@functools.lru_cache(maxsize=64)
def _dp_tables(leaf_sizes: Tuple[int, ...], density: float, p: int,
               codec_name: str, schedule: Optional[str],
               alpha_ms: float, beta_gbps: float, mode: str,
               pipeline: str = PIPELINE_DEFAULT):
    """All-B partition DP over contiguous buckets.

    dp[b][i] = best (cost_ms, max_bucket_elems) of splitting the first i
    leaves into exactly b buckets; arg[b][i] the split point realizing
    it. The lexicographic value makes the cost-optimal partition also
    byte-balanced: among equal-cost partitions the one whose LARGEST
    bucket is smallest wins, which is the tie that matters when the
    codec makes cost insensitive to where a boundary falls.

    Returns (dp, arg, segcost) with segcost[(j, i)] the single-bucket
    cost of leaves j..i-1 (reused by report/bench pricing).
    """
    L = len(leaf_sizes)
    prefix = [0]
    for s in leaf_sizes:
        prefix.append(prefix[-1] + s)

    @functools.lru_cache(maxsize=None)
    def seg(j: int, i: int) -> Tuple[float, int]:
        n_b = prefix[i] - prefix[j]
        k_b = k_for_density(n_b, density)
        return (stage_cost_ms(n_b, k_b, pipeline=pipeline, p=p,
                              codec=codec_name, schedule=schedule,
                              alpha_ms=alpha_ms, beta_gbps=beta_gbps,
                              mode=mode), n_b)

    INF = (math.inf, 0)
    dp: List[List[Tuple[float, int]]] = [[INF] * (L + 1) for _ in range(L + 1)]
    arg: List[List[int]] = [[-1] * (L + 1) for _ in range(L + 1)]
    dp[0][0] = (0.0, 0)
    for b in range(1, L + 1):
        # Exactly b buckets need at least b leaves; a bucket per leaf at
        # most, so i ranges b..L.
        for i in range(b, L + 1):
            best, best_j = INF, -1
            for j in range(b - 1, i):
                prev = dp[b - 1][j]
                if prev[0] == math.inf:
                    continue
                c, load = seg(j, i)
                cand = (prev[0] + c, max(prev[1], load))
                # Strict < keeps the SMALLEST split point on ties, i.e.
                # the earliest boundary — deterministic across runs.
                if cand < best:
                    best, best_j = cand, j
            dp[b][i] = best
            arg[b][i] = best_j
    return dp, arg, seg


def _backtrack(arg, b: int, L: int) -> Tuple[int, ...]:
    cuts = [L]
    i = L
    for bb in range(b, 0, -1):
        i = arg[bb][i]
        cuts.append(i)
    return tuple(reversed(cuts))


def optimal_boundaries(leaf_sizes: Sequence[int], density: float, *,
                       n_buckets: Optional[int], p: int, codec="fp32",
                       schedule: Optional[str] = None, alpha_ms: float,
                       beta_gbps: float,
                       mode: str = "gtopk_layerwise",
                       pipeline: str = PIPELINE_DEFAULT) -> Tuple[int, ...]:
    """Exact cost-minimal contiguous partition. ``n_buckets=None`` lets
    the DP choose B too; ties between bucket counts break toward the
    historical per-leaf end (LARGER B), so `auto` never coarsens the
    wire unless the modeled cost strictly improves."""
    sizes = tuple(int(s) for s in leaf_sizes)
    L = len(sizes)
    if L == 0:
        raise ValueError("cannot bucket zero leaves")
    codec_name = getattr(codec, "name", codec)
    dp, arg, _ = _dp_tables(sizes, float(density), int(p), str(codec_name),
                            schedule, float(alpha_ms), float(beta_gbps),
                            mode, str(pipeline))
    if n_buckets is not None:
        b = max(1, min(int(n_buckets), L))
        return _backtrack(arg, b, L)
    best_b, best = L, dp[L][L]
    for b in range(L - 1, 0, -1):  # historical-first: larger B wins ties
        if dp[b][L] < best:
            best_b, best = b, dp[b][L]
    return _backtrack(arg, best_b, L)


def plan_buckets(leaf_sizes: Sequence[int], density: float, *,
                 buckets=BUCKETS_DEFAULT, p: int = 1, codec="fp32",
                 schedule: Optional[str] = None,
                 alpha_ms: Optional[float] = None,
                 beta_gbps: Optional[float] = None,
                 probe_dir: Optional[str] = None,
                 mode: str = "gtopk_layerwise",
                 pipeline: str = PIPELINE_DEFAULT) -> Optional[BucketPlan]:
    """Resolve a --buckets spec against a model's leaf sizes.

    Returns None for 'concat' (the historical single-merge wire — no
    bucket axis exists there, and therefore no pipeline axis either).
    'leaf' and a pinned int are pure structure; 'auto' (and the
    boundary placement of a pinned B) needs alpha/beta — passed
    explicitly or read from the committed probe fit via the planner's
    inputs (parallel.planner.planner_inputs).

    ``pipeline`` resolution also lives here: 'serial'/'overlap' are
    taken as pinned (the DP prices under that order); 'auto' runs the
    DP under BOTH pricings, compares the true modeled spans
    (pipeline_span_ms) of the two winners, and keeps the cheaper —
    ties go to 'serial', the historical order."""
    spec = parse_buckets(buckets)
    if spec == "concat":
        return None
    pipe = parse_pipeline(pipeline)
    sizes = tuple(int(s) for s in leaf_sizes)
    L = len(sizes)
    if L == 0:
        raise ValueError("cannot bucket zero leaves")

    def per_bucket_ks(bounds: Tuple[int, ...]) -> Tuple[int, ...]:
        return tuple(
            k_for_density(sum(sizes[lo:hi]), density)
            for lo, hi in zip(bounds, bounds[1:]))

    # 'leaf' structure needs no pricing, but resolving pipeline 'auto'
    # still does; only fetch probe inputs when something will use them.
    needs_pricing = spec != "leaf" or pipe == "auto"
    if needs_pricing and (alpha_ms is None or beta_gbps is None):
        # Late import: planner imports ledger, and pulling it at module
        # import time would cycle through parallel/__init__.
        from .planner import planner_inputs
        inputs = planner_inputs(probe_dir)
        alpha_ms = inputs["alpha_ms"] if alpha_ms is None else alpha_ms
        beta_gbps = inputs["beta_gbps"] if beta_gbps is None else beta_gbps

    def span(plan: BucketPlan) -> float:
        return pipeline_span_ms(plan, p=p, codec=codec, schedule=schedule,
                                alpha_ms=alpha_ms, beta_gbps=beta_gbps,
                                mode=mode)

    if spec == "leaf":
        bounds = _leaf_boundaries(L)
        plans = [BucketPlan(bounds, sizes, per_bucket_ks(bounds),
                            spec="leaf", pipeline=pp)
                 for pp in (("serial", "overlap") if pipe == "auto"
                            else (pipe,))]
        # Strict < keeps 'serial' (listed first) on ties.
        return min(plans, key=span) if len(plans) > 1 else plans[0]

    n_target = None if spec == "auto" else int(spec)

    def solve(pp: str) -> BucketPlan:
        bounds = optimal_boundaries(
            sizes, density, n_buckets=n_target, p=p, codec=codec,
            schedule=schedule, alpha_ms=alpha_ms, beta_gbps=beta_gbps,
            mode=mode, pipeline=pp)
        return BucketPlan(bounds, sizes, per_bucket_ks(bounds),
                          spec=buckets_key(spec), pipeline=pp)

    if pipe != "auto":
        return solve(pipe)
    serial_plan, overlap_plan = solve("serial"), solve("overlap")
    # min() keeps the first argument on ties — serial, the historical
    # order, so 'auto' only pipelines when the modeled span strictly
    # improves.
    return min((serial_plan, overlap_plan), key=span)


def describe(plan: BucketPlan, *, p: int, codec="fp32",
             schedule: Optional[str] = None, alpha_ms: float,
             beta_gbps: float,
             mode: str = "gtopk_layerwise") -> List[dict]:
    """Per-bucket rows for `report plan` / the bench: leaf range, elems,
    wire k, modeled bytes and ms (merge, select, and the pipeline-stage
    term the DP priced)."""
    rows = []
    for b, (n_b, k_b) in enumerate(plan.pairs()):
        lo, hi = plan.leaf_range(b)
        merge_ms = bucket_cost_ms(
            n_b, k_b, p=p, codec=codec, schedule=schedule,
            alpha_ms=alpha_ms, beta_gbps=beta_gbps, mode=mode)
        rows.append({
            "bucket": b,
            "leaves": f"{lo}:{hi}",
            "n_leaves": hi - lo,
            "elems": n_b,
            "k": k_b,
            "wire_bytes": comm_bytes_per_step(
                mode, n_b, k_b, p, codec=getattr(codec, "name", codec),
                schedule=schedule),
            "modeled_ms": merge_ms,
            "select_ms": select_cost_ms(n_b),
            "stage_ms": (max(select_cost_ms(n_b), merge_ms)
                         if plan.pipeline == "overlap" else merge_ms),
        })
    return rows
