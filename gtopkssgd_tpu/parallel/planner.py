"""Topology-aware comm planner: wire plans, scored once at startup.

Before this module, the wire algorithm was welded to the compression
mode: gtopk meant the hypercube tree, allgather meant the DGC union, and
adding a schedule meant threading a new mode string through every
dispatch table. The planner splits those concerns. A mode fixes the
SEMANTICS (what sparse set is applied, what repair contract the
optimizer gets); a :class:`CommPlan` fixes the WIRE — per-axis
algorithm, schedule, codec, and ici/dcn split — and is chosen ONCE at
startup by scoring every semantics-preserving candidate with the same
alpha-beta model the comm ledger audits against
(``benchmarks/scaling_model.predict`` via ``obs.ledger.predict_comm_ms``,
parameterized from a ``dcn_probe`` ``alpha_beta_fit`` artifact when one
is present, pure alpha-beta fallback otherwise).

Candidate sets are deliberately semantics-preserving: the planner never
swaps gtopk for allgather behind the user's back — it only picks among
wire realizations of the mode the user asked for (today: the hypercube
'tree' vs the Ok-Topk 'balanced' split-and-reduce, arXiv:2201.07598).
Ties and model-indifferent regimes resolve to the hand-picked historical
schedule (:func:`gtopkssgd_tpu.modes.default_schedule`), so default runs
keep their exact pre-planner wire. ``--comm-plan`` pins a plan by name;
the full decision — chosen plan plus the score of every candidate — is
logged as a ``"plan"`` metrics record and stamped into the run manifest,
so every ledger row can be traced back to why its schedule won.

Import discipline: scoring needs obs.ledger, and obs imports parallel —
so the ledger import is lazy (inside functions), keeping
``parallel.planner`` importable from ``parallel/__init__`` without a
cycle. Collectives never import the planner: ``sparse_allreduce`` takes
the plan duck-typed (anything with ``.schedule``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Tuple

from gtopkssgd_tpu.modes import (
    ALLGATHER_MODES,
    DENSE_MODES,
    GTOPK_MODES,
    HIER_MODES,
    LAYERWISE_MODES,
    default_schedule,
)
from gtopkssgd_tpu.parallel.collectives import (
    balanced_cap,
    comm_bytes_per_step,
)

# Per-message slow-link latency assumed when NO dcn_probe artifact is
# available (benchmarks/results/dcn_probe_*proc.json). Deliberately
# nonzero: the degenerate alpha=0 bandwidth-only model would let any
# many-small-messages schedule (balanced sends O(p) messages where the
# tree sends O(log p)) win on volume alone and silently change the wire
# at defaults. 0.1 ms is a conservative floor for any cross-host fabric;
# the committed 4-proc probe fit measured ~21.9 ms on loopback-TCP.
PLANNER_DEFAULT_ALPHA_MS = 0.1


@dataclasses.dataclass(frozen=True)
class CommPlan:
    """One fully-specified wire realization of a reduction mode.

    ``schedule`` is the slow-axis algorithm (modes.SCHEDULES), ``intra``
    the ICI-axis phase ('psum' for the hier mode's in-slice dense
    allreduce, 'none' otherwise), ``codec`` the sparse payload codec
    spec, ``ici_size`` the ICI-domain width the plan assumes,
    ``bucketing`` the layerwise merge granularity
    (parallel.bucketing.buckets_key grammar: 'concat' = the historical
    single concatenated merge, 'leaf' = one merge per leaf, 'b{B}' /
    'auto' = the DP partition), ``pipeline`` the RESOLVED execution
    order of the bucketed select/merge chain (modes.PIPELINES —
    'serial' is the historical strictly-sequential step, 'overlap' the
    double-buffered stage loop; resolution of an 'auto' spec happens
    upstream in parallel.bucketing.plan_buckets, the planner carries
    and records the outcome). The name is the plan grammar the
    ``--comm-plan`` flag speaks.
    """

    name: str
    mode: str
    schedule: str
    intra: str = "none"
    codec: str = "fp32"
    ici_size: int = 1
    bucketing: str = "concat"
    pipeline: str = "serial"

    @property
    def wire_mode(self) -> str:
        """Comm-model key (scaling_model.predict / ledger) this plan
        prices as — the single mapping shared with the ledger."""
        from gtopkssgd_tpu.obs.ledger import wire_mode_for
        return wire_mode_for(self.mode, self.schedule,
                             bucketing=self.bucketing)


def _norm_mode(mode: Optional[str]) -> str:
    return "dense" if mode in DENSE_MODES else str(mode)


def candidate_plans(mode: Optional[str], *, codec: str = "fp32",
                    ici_size: int = 1, bucketing: str = "concat",
                    pipeline: str = "serial") -> Tuple[CommPlan, ...]:
    """Every wire plan that realizes ``mode``'s semantics, historical
    default FIRST (selection uses a stable min, so the default wins all
    ties and all model-indifferent regimes). ``bucketing``/``pipeline``
    are carried on the gtopk-family candidates only — they are layerwise
    merge granularity / execution order, orthogonal to which schedule
    each merge runs."""
    m = _norm_mode(mode)
    if m in DENSE_MODES:
        return (CommPlan("dense", m, "psum", "none", codec, 1),)
    if m in ALLGATHER_MODES:
        return (CommPlan("allgather", m, "allgather", "none", codec, 1),)
    if m in HIER_MODES:
        # The hier tree already IS a planned ici/dcn split; a balanced
        # cross-slice variant would need slice-identical owner ranges
        # and is future work — the plan layer makes it additive.
        return (CommPlan("hier", m, "tree", "psum", codec,
                         max(1, ici_size)),)
    if m in GTOPK_MODES or m in LAYERWISE_MODES:
        return (CommPlan("tree", m, "tree", "none", codec, 1, bucketing,
                         pipeline),
                CommPlan("balanced", m, "balanced", "none", codec, 1,
                         bucketing, pipeline))
    raise ValueError(f"unknown mode {mode!r}")


def validate_pin(pin: Optional[str], mode: Optional[str], *,
                 ici_size: int = 1) -> str:
    """Normalize and check a ``--comm-plan`` pin against the mode's
    candidate set at config time — a typo'd or incompatible pin fails
    at startup, not three imports deep into the first traced step."""
    pin = "auto" if pin in (None, "", "auto") else str(pin)
    if pin == "auto":
        return pin
    names = [c.name for c in candidate_plans(mode, ici_size=ici_size)]
    if pin not in names:
        raise ValueError(
            f"--comm-plan {pin!r} does not realize mode {mode!r}; "
            f"valid plans here: auto, {', '.join(names)}")
    return pin


def planner_inputs(probe_dir: Optional[str] = None) -> Dict[str, Any]:
    """The alpha-beta constants the planner scores with, plus where they
    came from: the newest fit artifact (dcn_probe / calib_fit) when one
    exists, else documented fallback defaults (PLANNER_DEFAULT_ALPHA_MS
    + the scaling model's DCN bandwidth).

    An artifact carrying a per-axis ``axes`` section prices each hop
    from its OWN measured fit: the "dcn" entry overrides the blended
    slow-link alpha/beta, and the "ici" entry's bandwidth replaces the
    DEFAULT_ICI_GBPS guess — so a hierarchical plan's two hops are
    scored from two measured links, with no caller change needed."""
    from gtopkssgd_tpu.obs import ledger
    fit = ledger.load_alpha_beta(search_dir=probe_dir)
    if fit is not None:
        out = {"alpha_ms": fit["alpha_ms"],
               "beta_gbps": fit["beta_gbps"],
               "ici_gbps": ledger.DEFAULT_ICI_GBPS,
               "fit_source": fit["source"]}
        # Theil-Sen residual noise floor, when the artifact records one
        # (calib_fit does; probe-era artifacts don't). The forecast
        # plane derives its uncertainty bands from this — absent means
        # absent, not zero-by-decree.
        if "resid_ms" in fit:
            out["resid_ms"] = fit["resid_ms"]
        axes = fit.get("axes")
        if isinstance(axes, dict):
            dcn = axes.get("dcn")
            if dcn is not None:
                out["alpha_ms"] = dcn["alpha_ms"]
                out["beta_gbps"] = dcn["beta_gbps"]
                if "resid_ms" in dcn:
                    out["resid_ms"] = dcn["resid_ms"]
            ici = axes.get("ici")
            if ici is not None:
                out["ici_gbps"] = ici["beta_gbps"]
            out["axes"] = {name: dict(ax)
                           for name, ax in sorted(axes.items())}
        return out
    return {"alpha_ms": PLANNER_DEFAULT_ALPHA_MS,
            "beta_gbps": ledger.DEFAULT_DCN_GBPS,
            "ici_gbps": ledger.DEFAULT_ICI_GBPS,
            "fit_source": "fallback-defaults"}


def score_plan(plan: CommPlan, p: int, *, n: int, k: int,
               alpha_ms: float, beta_gbps: float, ici_gbps: float,
               buckets: Optional[Tuple[Tuple[int, int], ...]] = None
               ) -> float:
    """Predicted comm_ms of one candidate — scaling_model.predict when
    benchmarks/ is present, the ledger's pure alpha-beta model
    otherwise. The same number the ledger later audits against measured
    T_comm, so a plan decision is always reconcilable post-hoc.
    ``buckets`` (the BucketPlan's ((n_b, k_b), ...) pairs) prices the
    bucketed wire as B independent merges."""
    from gtopkssgd_tpu.obs.ledger import predict_comm_ms
    return predict_comm_ms(
        plan.wire_mode, p, n=n, k=k, alpha_ms=alpha_ms,
        beta_gbps=beta_gbps, ici_gbps=ici_gbps,
        ici_size=plan.ici_size, codec=plan.codec, buckets=buckets)


@dataclasses.dataclass(frozen=True)
class PlanDecision:
    """A resolved plan plus the evidence: every candidate's score and
    the model inputs used. ``record()`` is the flat dict the trainer
    logs as the ``"plan"`` metrics record."""

    plan: CommPlan
    candidates: Tuple[Dict[str, Any], ...]
    inputs: Dict[str, Any]
    pin: str = "auto"

    def record(self) -> Dict[str, Any]:
        historical = default_schedule(self.plan.mode)
        return {
            "plan": self.plan.name,
            "schedule": self.plan.schedule,
            "wire_mode": self.plan.wire_mode,
            "mode": self.plan.mode,
            "intra": self.plan.intra,
            "bucketing": self.plan.bucketing,
            "pipeline": self.plan.pipeline,
            "pin": self.pin,
            # numeric so the gate smoke can pin "defaults kept the
            # historical wire" as a baseline check
            "plan_is_default": float(self.plan.schedule == historical),
            "candidates": list(self.candidates),
            **{key: self.inputs[key] for key in sorted(self.inputs)},
        }


def build_decision(mode: Optional[str], *, p: int, n: int, k: int,
                   codec: str = "fp32", ici_size: int = 1,
                   pin: Optional[str] = "auto",
                   probe_dir: Optional[str] = None,
                   alpha_ms: Optional[float] = None,
                   beta_gbps: Optional[float] = None,
                   ici_gbps: Optional[float] = None,
                   bucketing: str = "concat",
                   buckets: Optional[Tuple[Tuple[int, int], ...]] = None,
                   fit_source: Optional[str] = None,
                   pipeline: str = "serial") -> PlanDecision:
    """Score every candidate plan for (mode, mesh, n, k, codec) and pick
    one: the pinned plan when ``pin`` names one, else the cheapest under
    the model (stable min — the historical default wins ties). Explicit
    alpha/beta/ici arguments override the probe-artifact lookup (tests,
    what-if scoring); ``fit_source`` labels where such an override came
    from (the --comm-model-fit artifact's filename) in place of the
    generic "arg", so the decision record keeps real provenance.
    ``bucketing``/``buckets`` (the resolved --buckets key and the
    BucketPlan's (n_b, k_b) pairs) make the candidate scores price the
    bucketed wire — B merges, each over its bucket-local index space —
    instead of the single concatenated merge. ``pipeline`` is the
    RESOLVED execution order (plan_buckets already decided an 'auto'
    spec); the decision still selects the schedule by comm_ms — the
    wire cost is what the schedule controls — but every candidate row
    also records span_serial_ms/span_overlap_ms, the step-span the two
    execution orders would expose under that schedule, so the recorded
    decision shows what overlap bought."""
    pin = validate_pin(pin, mode, ici_size=ici_size)
    inputs = planner_inputs(probe_dir)
    override_source = fit_source if fit_source is not None else "arg"
    if alpha_ms is not None:
        inputs["alpha_ms"] = float(alpha_ms)
        inputs["fit_source"] = override_source
    if beta_gbps is not None:
        inputs["beta_gbps"] = float(beta_gbps)
        inputs["fit_source"] = override_source
    if ici_gbps is not None:
        inputs["ici_gbps"] = float(ici_gbps)
    cands = candidate_plans(mode, codec=codec, ici_size=ici_size,
                            bucketing=bucketing, pipeline=pipeline)
    # Span pricing needs the bucket shapes; a concat/unbucketed wire is
    # one bucket of the full (n, k) — both execution orders then expose
    # the same span (a B=1 pipeline has nothing to overlap), which is
    # exactly the honest answer for that wire.
    from gtopkssgd_tpu.parallel import bucketing as _bucketing
    span_pairs = buckets if buckets else ((n, k),)
    span_plan = _bucketing.BucketPlan(
        boundaries=tuple(range(len(span_pairs) + 1)),
        leaf_sizes=tuple(nb for nb, _ in span_pairs),
        ks=tuple(kb for _, kb in span_pairs))
    scored: List[Dict[str, Any]] = []
    for cand in cands:
        ms = score_plan(cand, p, n=n, k=k, alpha_ms=inputs["alpha_ms"],
                        beta_gbps=inputs["beta_gbps"],
                        ici_gbps=inputs["ici_gbps"], buckets=buckets)
        wire_bytes = (
            sum(comm_bytes_per_step(cand.mode, n_b, k_b, p,
                                    ici_size=cand.ici_size,
                                    codec=cand.codec,
                                    schedule=cand.schedule)
                for n_b, k_b in buckets)
            if buckets else
            comm_bytes_per_step(cand.mode, n, k, p,
                                ici_size=cand.ici_size, codec=cand.codec,
                                schedule=cand.schedule))
        spans = {
            pipe: _bucketing.pipeline_span_ms(
                span_plan, p=p, codec=cand.codec,
                schedule=cand.schedule, alpha_ms=inputs["alpha_ms"],
                beta_gbps=inputs["beta_gbps"], mode=cand.mode,
                pipeline=pipe)
            for pipe in ("serial", "overlap")}
        scored.append({
            "name": cand.name, "schedule": cand.schedule,
            "wire_mode": cand.wire_mode, "comm_ms": round(ms, 6),
            "wire_bytes": wire_bytes,
            "span_serial_ms": round(spans["serial"], 6),
            "span_overlap_ms": round(spans["overlap"], 6),
        })
    if pin != "auto":
        chosen = next(c for c in cands if c.name == pin)
    else:
        chosen = cands[min(range(len(cands)),
                           key=lambda i: scored[i]["comm_ms"])]
    inputs = {**inputs, "p": p, "n": n, "k": k, "codec": str(codec),
              "ici_size": ici_size}
    return PlanDecision(plan=chosen, candidates=tuple(scored),
                        inputs=inputs, pin=pin)


@functools.lru_cache(maxsize=None)
def resolve_plan(mode: Optional[str], p: int, n: int, k: int,
                 codec: str = "fp32", ici_size: int = 1,
                 pin: Optional[str] = "auto",
                 probe_dir: Optional[str] = None,
                 bucketing: str = "concat",
                 buckets: Optional[Tuple[Tuple[int, int], ...]] = None,
                 pipeline: str = "serial") -> CommPlan:
    """The optimizer's trace-time entry point: (mode, mesh, n, k, codec,
    pin) -> CommPlan, memoized — the decision is made once per distinct
    shape, never per step, and retracing costs a dict lookup. The
    bucketing key, (n_b, k_b) pairs, and resolved pipeline are part of
    the memo key, so a bucketed and an unbucketed run of the same shape
    resolve independently."""
    return build_decision(mode, p=p, n=n, k=k, codec=codec,
                          ici_size=ici_size, pin=pin,
                          probe_dir=probe_dir, bucketing=bucketing,
                          buckets=buckets, pipeline=pipeline).plan
