// Host-side data-prep runtime (reference parity: the C++ the reference
// leaned on lived in torchvision's native transforms and numpy's C core —
// SURVEY.md §2 C8/C10. The TPU feeds from the host, so per-image Python
// loops become the input bottleneck; this library does the per-pixel work
// in C++ behind ctypes.)
//
// Design: the caller (numpy side) draws all randomness (crop offsets, flip
// coins) so Python and C++ paths are bit-identical and unit-testable; C++
// only does the deterministic heavy loops, threaded across the batch.
//
// Build: g++ -O3 -shared -fPIC (see build.py); no external deps.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <functional>
#include <thread>
#include <vector>

namespace {

constexpr int kH = 32, kW = 32, kC = 3, kPad = 4;
constexpr int kPH = kH + 2 * kPad, kPW = kW + 2 * kPad;

// Reflect-pad one HWC image into a padded buffer (mode='reflect', matching
// numpy: index mirrors without repeating the edge pixel).
void reflect_pad(const uint8_t* in, uint8_t* out) {
  auto src = [&](int y, int x, int c) -> uint8_t {
    return in[(y * kW + x) * kC + c];
  };
  for (int y = 0; y < kPH; ++y) {
    int sy = y - kPad;
    if (sy < 0) sy = -sy;
    if (sy >= kH) sy = 2 * kH - 2 - sy;
    for (int x = 0; x < kPW; ++x) {
      int sx = x - kPad;
      if (sx < 0) sx = -sx;
      if (sx >= kW) sx = 2 * kW - 2 - sx;
      for (int c = 0; c < kC; ++c)
        out[(y * kPW + x) * kC + c] = src(sy, sx, c);
    }
  }
}

// uint8 end to end: images stay raw pixels through augmentation (the wire
// format is uint8 — 4x fewer H2D bytes — and mean/std normalization runs
// on device inside the jitted step, not here).
void augment_one(const uint8_t* in, uint8_t* out, int y0, int x0,
                 bool flip) {
  uint8_t padded[kPH * kPW * kC];
  reflect_pad(in, padded);
  for (int y = 0; y < kH; ++y) {
    for (int x = 0; x < kW; ++x) {
      int sx = flip ? (x0 + kW - 1 - x) : (x0 + x);
      const uint8_t* p = &padded[((y0 + y) * kPW + sx) * kC];
      uint8_t* q = &out[(y * kW + x) * kC];
      for (int c = 0; c < kC; ++c) q[c] = p[c];
    }
  }
}

void parallel_for(int n, const std::function<void(int, int)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int nt = std::max(1, std::min<int>(hw ? (int)hw : 1, n));
  if (nt == 1) { fn(0, n); return; }
  std::vector<std::thread> ts;
  int per = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int lo = t * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(fn, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// in/out: u8[B,32,32,3]; ys/xs: i32[B] crop offsets in [0,8]; flips:
// u8[B]. Fused reflect-pad(4) + crop + hflip, raw pixels in and out.
void cifar_augment_batch(const uint8_t* in, uint8_t* out, int b,
                         const int* ys, const int* xs,
                         const uint8_t* flips) {
  parallel_for(b, [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i)
      augment_one(in + (size_t)i * kH * kW * kC,
                  out + (size_t)i * kH * kW * kC, ys[i], xs[i],
                  flips[i] != 0);
  });
}

// Levenshtein distance between int sequences (CER/WER eval hot loop).
int edit_distance(const int32_t* a, int la, const int32_t* b, int lb) {
  if (la == 0) return lb;
  if (lb == 0) return la;
  std::vector<int> prev(lb + 1), cur(lb + 1);
  for (int j = 0; j <= lb; ++j) prev[j] = j;
  for (int i = 1; i <= la; ++i) {
    cur[0] = i;
    for (int j = 1; j <= lb; ++j)
      cur[j] = std::min({prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (a[i - 1] != b[j - 1] ? 1 : 0)});
    std::swap(prev, cur);
  }
  return prev[lb];
}

}  // extern "C"
