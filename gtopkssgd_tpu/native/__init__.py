"""Native host runtime: C++ data-prep library behind ctypes (reference
parity for the native code the reference consumed via torchvision/numpy —
SURVEY.md §2 "Native / C++ / CUDA components" table).

The library is compiled on demand with g++ (no pybind11 in this image —
plain C ABI + ctypes, per the environment constraints). Everything has a
pure-numpy fallback, so the package works with no toolchain; `available()`
reports which path is active.

Split of labor: Python/numpy draws ALL randomness (so native and fallback
paths are bit-identical and testable), C++ does the per-pixel/per-cell
loops, threaded across the batch.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "dataprep.cpp")
_SO = os.path.join(_DIR, "libgtopk_dataprep.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             _SRC, "-o", _SO],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        ):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.cifar_augment_batch.argtypes = [
            u8p, u8p, ctypes.c_int, i32p, i32p, u8p,
        ]
        lib.cifar_augment_batch.restype = None
        lib.edit_distance.argtypes = [i32p, ctypes.c_int, i32p, ctypes.c_int]
        lib.edit_distance.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def cifar_augment_batch(
    images: np.ndarray,  # u8[B,32,32,3] raw pixels
    ys: np.ndarray,      # i32[B] crop offsets in [0, 8]
    xs: np.ndarray,
    flips: np.ndarray,   # bool[B]
) -> np.ndarray:
    """Fused reflect-pad(4) + random-crop(32) + hflip, uint8 in and out.

    Raw pixels stay raw: the wire format is uint8 (4x fewer H2D bytes)
    and mean/std normalization runs on device inside the jitted step.
    Native when the library is available, else the numpy reference
    implementation — bit-identical results either way.
    """
    images = np.ascontiguousarray(images, np.uint8)
    b = images.shape[0]
    lib = load()
    if lib is not None:
        out = np.empty_like(images)
        lib.cifar_augment_batch(
            images, out, b,
            np.ascontiguousarray(ys, np.int32),
            np.ascontiguousarray(xs, np.int32),
            np.ascontiguousarray(flips, np.uint8),
        )
        return out
    # numpy fallback (same semantics)
    padded = np.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect")
    out = np.empty_like(images)
    for i in range(b):
        crop = padded[i, ys[i]:ys[i] + 32, xs[i]:xs[i] + 32]
        out[i] = crop[:, ::-1] if flips[i] else crop
    return out


def edit_distance(a, b) -> int:
    """Levenshtein distance between two int sequences."""
    lib = load()
    if lib is not None:
        aa = np.ascontiguousarray(a, np.int32)
        bb = np.ascontiguousarray(b, np.int32)
        return int(lib.edit_distance(aa, len(aa), bb, len(bb)))
    if not len(a):
        return len(b)
    if not len(b):
        return len(a)
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[-1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]
