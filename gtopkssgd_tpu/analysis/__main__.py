"""CLI: ``python -m gtopkssgd_tpu.analysis [paths...]``.

Exit codes come from the registry this tool itself enforces
(gtopkssgd_tpu.exit_codes): 0 clean, 1 non-baselined findings, 2 usage.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence

from gtopkssgd_tpu.analysis import engine, reporters
from gtopkssgd_tpu.analysis.rules import ALL_RULES, RULES_BY_NAME

DEFAULT_BASELINE = "graftlint_baseline.json"


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        "gtopkssgd_tpu.analysis",
        description="graftlint: AST invariant checker for the jitted "
                    "hot path, the metric/exit-code registries, and "
                    "codec-mediated collectives.")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to scan (default: the "
                         "gtopkssgd_tpu package next to this analyzer)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help=f"baseline JSON of grandfathered findings "
                         f"(default: ./{DEFAULT_BASELINE} when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: report every finding")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="grandfather the current findings into PATH "
                         "(carries forward reasons for unchanged keys) "
                         "and exit 0")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="RULE", help="run only this rule (repeat "
                                         "for several)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print suppressed/baselined findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            doc = (rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.name:20s} {doc}")
        return 0

    if args.rule:
        unknown = sorted(set(args.rule) - set(RULES_BY_NAME))
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2

    paths = args.paths or [os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))]
    for p in paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    baseline = {}
    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)
    if baseline_path and not args.no_baseline:
        try:
            baseline = engine.load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    result = engine.run(
        paths, rules=ALL_RULES, baseline=baseline,
        rule_names=set(args.rule) if args.rule else None)

    if args.write_baseline:
        engine.write_baseline(
            args.write_baseline,
            result.findings + result.baselined, old=baseline)
        print(f"wrote {len(result.findings) + len(result.baselined)} "
              f"baseline entries to {args.write_baseline}")
        return 0

    if args.json:
        reporters.json_report(result, sys.stdout)
    else:
        reporters.text_report(result, sys.stdout, verbose=args.verbose)
    return 1 if result.findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
