"""Jit-reachability and value-taint machinery for graftlint rules.

Answers two questions from source alone:

  1. Which functions can run INSIDE a jit/pmap/shard_map trace?  Entry
     points come from decorators (``@jax.jit``, ``@functools.partial(
     jax.jit, ...)``), from wrapper call sites (``jax.jit(f)``,
     ``jax.shard_map(step, ...)`` — including nested defs the trainer's
     step builders produce), and from an explicit seed list for
     functions whose jit context is a calling convention rather than a
     visible wrapper (everything in ``parallel/collectives.py`` runs
     inside a shard_map body by module contract; the optimizer's
     ``update_fn``/``init_fn`` closures are installed as the
     GradientTransformation the jitted step calls).  Reachability is the
     transitive closure over *name references* (not just direct calls),
     so ``lax.scan(body, ...)`` and helpers passed as values are
     followed.

  2. Which local names hold TRACED values?  Per function, a fixpoint
     taint: values produced by jnp/lax calls are traced, and taint flows
     through assignments; lambda parameters count (tree.map/scan
     callbacks run over traced leaves).  Function PARAMETERS are *not*
     assumed traced — in this codebase the static config plumbed through
     jit-reachable helpers (densities, axis sizes, block sizes, layer
     size lists) arrives as parameters, and ``float(density)`` /
     ``int(math.log2(q))`` is trace-time host arithmetic, not a sync.
     Static shape metadata (``x.shape``/``.size``/``.ndim``/``.dtype``)
     is exempt — ``int(leaf.size)`` is host arithmetic at trace time,
     not a sync.

Nested ``def``s are separate functions (a builder method that CONTAINS
a jitted step is not itself hot); ``lambda``s are treated as part of
their enclosing function (they are tree.map/scan callbacks whose
parameters are traced).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from gtopkssgd_tpu.analysis.engine import SourceFile

# Wrappers whose callee (decorated function / first argument) traces.
JIT_WRAPPERS = {
    "jax.jit", "jax.pmap", "jax.shard_map", "jit", "pmap", "shard_map",
    "pjit", "jax.experimental.pjit.pjit",
    "jax.experimental.shard_map.shard_map",
}
_PARTIALS = {"functools.partial", "partial"}

# Attribute reads that are static at trace time (no device sync).
STATIC_ATTRS = {"shape", "size", "ndim", "dtype", "sharding", "name"}

# Default seeds: (module rel-path suffix, function-name regex).
DEFAULT_SEEDS: Tuple[Tuple[str, str], ...] = (
    # Module contract: every function runs inside a shard_map body.
    ("parallel/collectives.py", r".*"),
    # Installed as the GradientTransformation the jitted step calls.
    ("optimizer.py", r"^(update_fn|layerwise_update|init_fn"
                     r"|sparse_branch|dense_branch)$"),
    # Wire codec encode/decode run inside every exchange round.
    ("parallel/codec.py", r"^(encode|decode)$"),
)


@dataclasses.dataclass
class FuncInfo:
    sf: SourceFile
    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    qualname: str
    params: Set[str]
    parent: Optional["FuncInfo"]

    @property
    def name(self) -> str:
        return self.node.name  # type: ignore[attr-defined]


class ModuleInfo:
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.funcs: List[FuncInfo] = []
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.import_alias: Dict[str, str] = {}   # alias -> dotted module
        self.from_names: Dict[str, Tuple[str, str]] = {}  # name->(mod,orig)
        self._index()

    def _index(self) -> None:
        for node in ast.walk(self.sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_alias[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
                    if a.asname:
                        self.import_alias[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_names[a.asname or a.name] = (
                        node.module, a.name)

        def visit(node: ast.AST, parent: Optional[FuncInfo],
                  prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    fi = FuncInfo(
                        sf=self.sf, node=child, qualname=qual,
                        params=_param_names(child.args), parent=parent)
                    self.funcs.append(fi)
                    self.by_name.setdefault(child.name, []).append(fi)
                    visit(child, fi, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, parent, f"{prefix}{child.name}.")
                else:
                    visit(child, parent, prefix)

        visit(self.sf.tree, None, "")

    def full_name(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with the root resolved
        through this module's imports (``from jax import lax`` makes
        ``lax.psum`` -> ``jax.lax.psum``)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = node.id
        if root in self.import_alias:
            root = self.import_alias[root]
        elif root in self.from_names:
            mod, orig = self.from_names[root]
            root = f"{mod}.{orig}"
        parts.append(root)
        return ".".join(reversed(parts))


def _param_names(args: ast.arguments) -> Set[str]:
    names = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


def own_statements(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's body EXCLUDING nested def subtrees (they are
    separate functions) but INCLUDING lambdas (inline callbacks)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def value_bindings(fi: FuncInfo) -> Set[str]:
    """Names bound to VALUES in ``fi`` or an enclosing function scope:
    parameters, assignment/loop/with/except targets.  A bare reference
    to such a name is the local value, never a same-named module-level
    function — ``_loss_fn(params, batch, train=True)``'s ``train`` flag
    must not resolve to ``Trainer.train``.  Nested ``def`` names are
    deliberately NOT included: referencing one is a real call edge."""
    names: Set[str] = set()
    cur: Optional[FuncInfo] = fi
    while cur is not None:
        names |= cur.params
        for node in own_statements(cur.node):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                                   ast.NamedExpr)):
                targets = [node.target]
            elif isinstance(node, ast.For):
                targets = [node.target]
            elif isinstance(node, ast.comprehension):
                targets = [node.target]
            elif isinstance(node, ast.withitem) and node.optional_vars:
                targets = [node.optional_vars]
            elif isinstance(node, ast.ExceptHandler) and node.name:
                names.add(node.name)
            for t in targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        names.add(leaf.id)
        cur = cur.parent
    return names


class CallGraph:
    """Whole-file-set function index + jit reachability."""

    def __init__(self, files: Sequence[SourceFile],
                 seeds: Sequence[Tuple[str, str]] = DEFAULT_SEEDS):
        self.modules = [ModuleInfo(sf) for sf in files]
        self.by_rel = {m.sf.rel: m for m in self.modules}
        # Global bare-name index for cross-module from-import resolution.
        self.global_by_name: Dict[str, List[FuncInfo]] = {}
        for m in self.modules:
            for fi in m.funcs:
                self.global_by_name.setdefault(fi.name, []).append(fi)
        self.entries: Set[int] = set()      # id(FuncInfo.node)
        self.reachable: Dict[int, FuncInfo] = {}
        self._find_entries(seeds)
        self._close_over_references()

    # ----------------------------------------------------------- entries
    def _is_jit_wrapper(self, m: ModuleInfo, func: ast.AST) -> bool:
        name = m.full_name(func)
        if name in JIT_WRAPPERS:
            return True
        # functools.partial(jax.jit, ...) used as a decorator factory.
        if (isinstance(func, ast.Call)
                and m.full_name(func.func) in _PARTIALS and func.args):
            return m.full_name(func.args[0]) in JIT_WRAPPERS
        return False

    def _find_entries(self, seeds: Sequence[Tuple[str, str]]) -> None:
        for m in self.modules:
            for fi in m.funcs:
                for deco in fi.node.decorator_list:  # type: ignore
                    target = deco.func if isinstance(deco, ast.Call) \
                        else deco
                    if self._is_jit_wrapper(m, target) or (
                            isinstance(deco, ast.Call)
                            and self._is_jit_wrapper(m, deco)):
                        self._mark(fi)
                for suffix, pattern in seeds:
                    if (m.sf.rel.endswith(suffix)
                            and re.match(pattern, fi.name)):
                        self._mark(fi)
            for node in ast.walk(m.sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not self._is_jit_wrapper(m, node.func):
                    continue
                if node.args:
                    self._mark_callee_expr(m, node.args[0])

    def _mark_callee_expr(self, m: ModuleInfo, expr: ast.AST) -> None:
        if isinstance(expr, ast.Name):
            for fi in m.by_name.get(expr.id, []):
                self._mark(fi)
        elif isinstance(expr, ast.Call):
            # jax.jit(jax.shard_map(f, ...)): the inner call is itself
            # scanned by _find_entries, nothing extra to do — but a
            # plain wrapper like jax.jit(functools.partial(f, ...))
            # still resolves through the partial's first argument.
            if m.full_name(expr.func) in _PARTIALS and expr.args:
                self._mark_callee_expr(m, expr.args[0])
        # Lambdas passed to jax.jit directly have no FuncInfo; their
        # bodies are part of the enclosing function's statements and
        # are covered when that function is reachable.

    def _mark(self, fi: FuncInfo) -> None:
        if id(fi.node) not in self.reachable:
            self.entries.add(id(fi.node))
            self.reachable[id(fi.node)] = fi

    # ------------------------------------------------------- reachability
    def _resolve_reference(self, m: ModuleInfo,
                           node: ast.AST) -> List[FuncInfo]:
        if isinstance(node, ast.Name):
            local = m.by_name.get(node.id)
            if local:
                return local
            if node.id in m.from_names:
                mod, orig = m.from_names[node.id]
                return self._resolve_imported(mod, orig)
            return []
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self":
                    return m.by_name.get(node.attr, [])
                dotted = m.import_alias.get(base.id)
                if dotted is None and base.id in m.from_names:
                    fmod, forig = m.from_names[base.id]
                    dotted = f"{fmod}.{forig}"
                if dotted:
                    return self._resolve_imported(dotted, node.attr)
        return []

    def _resolve_imported(self, module: str, name: str) -> List[FuncInfo]:
        rel = module.replace(".", "/") + ".py"
        target = None
        for m in self.modules:
            if m.sf.rel == rel or m.sf.rel.endswith("/" + rel):
                target = m
                break
        if target is not None and name in target.by_name:
            return target.by_name[name]
        # Package __init__ re-exports: fall back to the global bare-name
        # index for package-internal modules only.
        if module.split(".")[0] in {
                m.sf.rel.split("/")[0] for m in self.modules}:
            return self.global_by_name.get(name, [])
        return []

    def _close_over_references(self) -> None:
        work = list(self.reachable.values())
        shadow_cache: Dict[int, Set[str]] = {}
        while work:
            fi = work.pop()
            m = self.by_rel[fi.sf.rel]
            shadowed = shadow_cache.get(id(fi.node))
            if shadowed is None:
                shadowed = shadow_cache[id(fi.node)] = value_bindings(fi)
            for node in own_statements(fi.node):
                if not isinstance(node, (ast.Name, ast.Attribute)):
                    continue
                if isinstance(node, ast.Name) and not isinstance(
                        getattr(node, "ctx", None), ast.Load):
                    continue
                if isinstance(node, ast.Name) and node.id in shadowed:
                    continue  # local value, not a module-level function
                for target in self._resolve_reference(m, node):
                    if id(target.node) not in self.reachable:
                        self.reachable[id(target.node)] = target
                        work.append(target)

    def reachable_functions(self) -> List[FuncInfo]:
        return sorted(self.reachable.values(),
                      key=lambda fi: (fi.sf.rel, fi.node.lineno))


# ---------------------------------------------------------------- taint

# Calls rooted here produce device values no matter the arguments
# (jnp.zeros of a static shape is still a traced array) ...
_ALWAYS_TRACED_ROOTS = {"jnp", "lax"}
# ... while these only propagate taint that flows in through an argument
# (np.asarray of a static python list is host data).
_ARG_TRACED_ROOTS = {"jax", "np", "numpy"}


def traced_names(fi: FuncInfo) -> Set[str]:
    """Fixpoint over simple assignments: which local names (probably)
    hold traced values inside this jit-reachable function.  Parameters
    are NOT seeded (see module docstring): taint originates at jnp/lax
    producers and flows through assignments from there."""
    tainted: Set[str] = set()
    # Lambda parameters inside this function body: callbacks over traced
    # pytrees (tree.map, scan bodies) — treat as traced.
    for node in own_statements(fi.node):
        if isinstance(node, ast.Lambda):
            tainted |= _param_names(node.args)
    changed = True
    while changed:
        changed = False
        for node in own_statements(fi.node):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value:
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.NamedExpr):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.For):
                targets, value = [node.target], node.iter
            if value is None or not targets:
                continue
            if not expr_is_traced(value, tainted):
                continue
            for t in targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        if leaf.id not in tainted:
                            tainted.add(leaf.id)
                            changed = True
    return tainted


def expr_is_traced(expr: ast.AST, tainted: Set[str]) -> bool:
    """Does ``expr`` (likely) produce/contain a traced value?  Static
    shape metadata reads are exempt; calls rooted at jnp/jax/lax count
    as traced producers."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                continue  # x.shape / x.size / ... : host-static
            stack.append(node.value)
            continue
        if isinstance(node, ast.Call):
            root = node.func
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                if root.id in _ALWAYS_TRACED_ROOTS:
                    return True
                if (root.id in _ARG_TRACED_ROOTS
                        and any(expr_is_traced(a, tainted)
                                for a in node.args)):
                    return True
            stack.extend(node.args)
            stack.extend(kw.value for kw in node.keywords)
            if not isinstance(node.func, ast.Name):
                stack.append(node.func)
            continue
        if isinstance(node, ast.Name):
            if node.id in tainted:
                return True
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False
