"""graftlint core: file model, suppressions, baseline, rule runner.

Pure stdlib (ast/json/tokenize) on purpose — the analyzer must run on a
box with a dead accelerator tunnel and must never pay a JAX import.
Registry values it needs at analysis time (metric KINDS, the exit-code
registry) are themselves extracted from the package *source* by AST
(rules.py), so linting cannot trigger backend initialization.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set

# ``# graftlint: disable=rule-a,rule-b`` (or ``all``) on the flagged
# line or the line directly above it.
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\-\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to source.

    ``symbol`` is the qualified name of the enclosing function (or
    ``<module>``); ``snippet`` is the unparsed flagged expression. The
    baseline matches on (rule, path, symbol, snippet) — line numbers are
    display-only, so a baselined finding survives unrelated edits to the
    same file.
    """

    rule: str
    path: str      # repo-relative, forward slashes
    line: int
    col: int
    message: str
    symbol: str = "<module>"
    snippet: str = ""

    @property
    def baseline_key(self) -> str:
        return "::".join(
            (self.rule, self.path, self.symbol, self.snippet))

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


class SourceFile:
    """One parsed module: AST + per-line suppression sets."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.suppressions: Dict[int, Set[str]] = {}
        self._scan_suppressions(text)

    def _scan_suppressions(self, text: str) -> None:
        # tokenize (not a line regex) so a '# graftlint:' inside a string
        # literal is not a suppression.
        try:
            tokens = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.suppressions.setdefault(
                    tok.start[0], set()).update(rules)
        except tokenize.TokenError:
            pass

    def suppressed(self, finding: Finding) -> bool:
        for line in (finding.line, finding.line - 1):
            rules = self.suppressions.get(line)
            if rules and ("all" in rules or finding.rule in rules):
                return True
        return False


def _iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, files in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith("."))
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)


def load_files(paths: Sequence[str],
               root: Optional[str] = None) -> List[SourceFile]:
    """Parse every .py under ``paths``; ``root`` anchors the
    repo-relative names findings and baselines use (default: cwd)."""
    root = os.path.abspath(root or os.getcwd())
    out: List[SourceFile] = []
    for path in _iter_py_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root)
        with open(ap, encoding="utf-8") as fh:
            text = fh.read()
        try:
            out.append(SourceFile(ap, rel, text))
        except SyntaxError as e:
            # A file the interpreter would reject is its own finding —
            # surfaced by the runner, not silently skipped.
            sf = SourceFile.__new__(SourceFile)
            sf.path, sf.rel, sf.text = ap, rel.replace(os.sep, "/"), text
            sf.tree = None
            sf.suppressions = {}
            sf.syntax_error = e  # type: ignore[attr-defined]
            out.append(sf)
    return out


# ------------------------------------------------------------------ baseline

def load_baseline(path: str) -> Dict[str, dict]:
    """Baseline JSON -> {baseline_key: entry}. Schema: {"findings":
    [{"rule","path","symbol","snippet","reason"}...]} — ``reason`` is
    the mandatory one-line justification for grandfathering."""
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data.get("findings", [])
    out: Dict[str, dict] = {}
    for e in entries:
        key = "::".join((e.get("rule", ""), e.get("path", ""),
                         e.get("symbol", ""), e.get("snippet", "")))
        out[key] = e
    return out


def write_baseline(path: str, findings: Sequence[Finding],
                   old: Optional[Dict[str, dict]] = None) -> None:
    """Grandfather ``findings``, carrying forward reasons from an
    existing baseline where keys match; new entries get a TODO reason
    that review is expected to replace."""
    old = old or {}
    rows = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        prev = old.get(f.baseline_key, {})
        rows.append({
            "rule": f.rule,
            "path": f.path,
            "symbol": f.symbol,
            "snippet": f.snippet,
            "message": f.message,
            "reason": prev.get("reason",
                               "TODO: justify or fix this finding"),
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"findings": rows}, fh, indent=1, sort_keys=True)
        fh.write("\n")


# -------------------------------------------------------------------- runner

@dataclasses.dataclass
class Result:
    findings: List[Finding]            # actionable (not suppressed/baselined)
    suppressed: List[Finding]
    baselined: List[Finding]
    stale_baseline: List[str]          # baseline keys that no longer fire
    files_scanned: int


def analyze(files: Sequence[SourceFile],
            rules: Sequence,           # Sequence[Rule] (rules.py)
            rule_names: Optional[Set[str]] = None) -> List[Finding]:
    """Run rules over parsed files; returns RAW findings (suppressions
    and baseline are applied by ``run``)."""
    findings: List[Finding] = []
    broken = [f for f in files if f.tree is None]
    for f in broken:
        e = getattr(f, "syntax_error", None)
        findings.append(Finding(
            rule="syntax", path=f.rel,
            line=getattr(e, "lineno", 1) or 1,
            col=getattr(e, "offset", 0) or 0,
            message=f"file does not parse: {e}",
        ))
    parsed = [f for f in files if f.tree is not None]
    for rule in rules:
        if rule_names and rule.name not in rule_names:
            continue
        findings.extend(rule.run(parsed))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def run(paths: Sequence[str], *, rules: Sequence,
        baseline: Optional[Dict[str, dict]] = None,
        rule_names: Optional[Set[str]] = None,
        root: Optional[str] = None) -> Result:
    files = load_files(paths, root=root)
    raw = analyze(files, rules, rule_names=rule_names)
    by_rel = {f.rel: f for f in files}
    actionable: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    seen_keys: Set[str] = set()
    baseline = baseline or {}
    for f in raw:
        sf = by_rel.get(f.path)
        if sf is not None and sf.suppressed(f):
            suppressed.append(f)
        elif f.baseline_key in baseline:
            seen_keys.add(f.baseline_key)
            baselined.append(f)
        else:
            actionable.append(f)
    stale = sorted(set(baseline) - seen_keys)
    return Result(findings=actionable, suppressed=suppressed,
                  baselined=baselined, stale_baseline=stale,
                  files_scanned=len(files))
