"""The graftlint rule set — six invariants, each born from a real bug
or a convention that was previously enforced by grep, docstring, or
reviewer memory.

Registry-backed rules (metric-kind, exit-code, event-rule) read their
registries
from the package SOURCE by AST — never by import, which would
initialize a JAX backend — so the analyzer stays silicon-free. When the
scanned file set itself contains ``utils/metrics.py`` / a registry
module, that copy wins (fixture trees in tests override the installed
package); otherwise the files shipped next to this analyzer are read.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from gtopkssgd_tpu.analysis.callgraph import (
    CallGraph,
    FuncInfo,
    ModuleInfo,
    expr_is_traced,
    own_statements,
    traced_names,
)
from gtopkssgd_tpu.analysis.engine import Finding, SourceFile

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _snippet(node: ast.AST, limit: int = 80) -> str:
    try:
        text = ast.unparse(node)
    except Exception:
        text = type(node).__name__
    return text if len(text) <= limit else text[:limit - 3] + "..."


def _enclosing(sf: SourceFile, node: ast.AST) -> str:
    """Qualified name of the innermost function containing ``node``
    (line-range containment — good enough for display/baseline keys)."""
    best = "<module>"
    best_span = None
    target = getattr(node, "lineno", None)
    if target is None:
        return best
    stack: List[Tuple[ast.AST, str]] = [(sf.tree, "")]
    while stack:
        cur, prefix = stack.pop()
        for child in ast.iter_child_nodes(cur):
            name = getattr(child, "name", None)
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = f"{prefix}{name}"
                lo = child.lineno
                hi = max((getattr(n, "lineno", lo)
                          for n in ast.walk(child)), default=lo)
                if lo <= target <= hi and not isinstance(
                        child, ast.ClassDef):
                    span = hi - lo
                    if best_span is None or span <= best_span:
                        best, best_span = qual, span
                stack.append((child, qual + "."))
            else:
                stack.append((child, prefix))
    return best


def _finding(rule: str, sf: SourceFile, node: ast.AST, message: str,
             symbol: Optional[str] = None) -> Finding:
    return Finding(
        rule=rule, path=sf.rel,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=message,
        symbol=symbol or _enclosing(sf, node),
        snippet=_snippet(node))


# --------------------------------------------------------------------------
# Registry extraction (AST only — see module docstring).
# --------------------------------------------------------------------------

def _load_source(files: Sequence[SourceFile],
                 rel_suffix: str) -> Optional[ast.AST]:
    for sf in files:
        if sf.rel.endswith(rel_suffix):
            return sf.tree
    fallback = os.path.join(_PKG_DIR, *rel_suffix.split("/"))
    if os.path.exists(fallback):
        with open(fallback, encoding="utf-8") as fh:
            return ast.parse(fh.read(), filename=fallback)
    return None


def registered_kinds(files: Sequence[SourceFile] = ()) -> Set[str]:
    """``utils.metrics.KINDS`` recovered from source."""
    tree = _load_source(files, "utils/metrics.py")
    kinds: Set[str] = set()
    if tree is None:
        return kinds
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "KINDS"
                   for t in node.targets):
            continue
        for leaf in ast.walk(node.value):
            if isinstance(leaf, ast.Constant) and isinstance(
                    leaf.value, str):
                kinds.add(leaf.value)
    return kinds


def registered_event_rules(files: Sequence[SourceFile] = ()) -> Set[str]:
    """``obs.events.RULES`` (the anomaly rule-name registry) recovered
    from source, same AST-only discipline as ``registered_kinds``."""
    tree = _load_source(files, "obs/events.py")
    rules: Set[str] = set()
    if tree is None:
        return rules
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "RULES"
                   for t in node.targets):
            continue
        for leaf in ast.walk(node.value):
            if isinstance(leaf, ast.Constant) and isinstance(
                    leaf.value, str):
                rules.add(leaf.value)
    return rules


def exit_code_registry(
        files: Sequence[SourceFile] = ()) -> Dict[int, List[str]]:
    """``gtopkssgd_tpu.exit_codes`` constants from source:
    {code: [names...]} — more than one name per code is a collision."""
    tree = _load_source(files, "exit_codes.py")
    out: Dict[int, List[str]] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
                and not isinstance(node.value.value, bool)):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id.startswith("EXIT_"):
                out.setdefault(node.value.value, []).append(t.id)
    return out


# --------------------------------------------------------------------------
# Rule 1: host-sync-in-jit
# --------------------------------------------------------------------------

class HostSyncInJitRule:
    """No host synchronization inside the jitted hot path.

    The dispatch-stall watchdog (obs/watchdog.py) exists because a
    single blocking host read of a device value once hung a run for its
    whole uptime window. This rule makes the invariant static: build
    the jit/pmap/shard_map reachability set (callgraph.py) and flag,
    inside it, ``.item()``, ``jax.device_get``, ``float()``/``int()``
    coercions of traced values, ``np.asarray`` of traced values, and
    ``print`` of traced values.
    """

    name = "host-sync-in-jit"

    _COERCIONS = {"float", "int", "bool", "complex"}

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        graph = CallGraph(files)
        findings: List[Finding] = []
        for fi in graph.reachable_functions():
            m = graph.by_rel[fi.sf.rel]
            tainted = traced_names(fi)
            for node in own_statements(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                findings.extend(
                    self._check_call(m, fi, node, tainted))
        return findings

    def _check_call(self, m: ModuleInfo, fi: FuncInfo, node: ast.Call,
                    tainted: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        func = node.func
        where = f"jit-reachable `{fi.qualname}`"

        def flag(msg: str) -> None:
            out.append(_finding(self.name, fi.sf, node,
                                f"{msg} inside {where}",
                                symbol=fi.qualname))

        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not node.args and not node.keywords:
            flag("`.item()` forces a device->host sync")
            return out
        full = m.full_name(func)
        if full in {"jax.device_get", "device_get"}:
            flag("`jax.device_get` forces a device->host transfer")
            return out
        if full in {"np.asarray", "numpy.asarray", "np.array",
                    "numpy.array"} and node.args and expr_is_traced(
                        node.args[0], tainted):
            flag(f"`{full}` of a traced value forces a host transfer")
            return out
        if isinstance(func, ast.Name):
            if func.id == "print" and any(
                    expr_is_traced(a, tainted) for a in node.args):
                flag("`print` of a traced value syncs (use jax.debug."
                     "print for traced debugging)")
            elif (func.id in self._COERCIONS and len(node.args) == 1
                    and expr_is_traced(node.args[0], tainted)):
                flag(f"`{func.id}()` of a traced value blocks on the "
                     "dispatched computation")
        return out


# --------------------------------------------------------------------------
# Shared .log( call-site model (rules 2 and 5)
# --------------------------------------------------------------------------

_LOG_EXCLUDED_ROOTS = {"np", "jnp", "numpy", "math", "logging", "torch"}


def _metric_log_calls(m: ModuleInfo):
    """Yield (call, resolved_kind | None, reason) for every call site
    that looks like ``MetricsLogger.log`` — an attribute call named
    ``log`` with a positional first argument, excluding numeric/stdlib
    ``log`` receivers (np.log, math.log, Logger handles named *logger*).
    resolved_kind is the first argument as a string when it is a
    literal or a name statically bound to one; reason explains the
    failure otherwise ("f-string", "unresolved")."""
    for node in ast.walk(m.sf.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "log" and node.args):
            continue
        recv = m.full_name(node.func.value)
        if recv:
            parts = recv.split(".")
            if parts[0] in _LOG_EXCLUDED_ROOTS or any(
                    "logger" in p.lower() for p in parts):
                continue
        kind, reason = _resolve_kind(m, node, node.args[0])
        yield node, kind, reason


def _resolve_kind(m: ModuleInfo, call: ast.Call,
                  arg: ast.AST) -> Tuple[Optional[str], str]:
    if isinstance(arg, ast.Constant):
        if isinstance(arg.value, str):
            return arg.value, ""
        return None, f"non-string literal {arg.value!r}"
    if isinstance(arg, ast.JoinedStr):
        return None, "f-string (dynamic kind)"
    if isinstance(arg, ast.Name):
        # Nearest static binding: a function-local `k = "obs"` wins over
        # a module-level constant of the same name.
        for scope in (_enclosing_node(m.sf.tree, call), m.sf.tree):
            if scope is None:
                continue
            bound = _string_binding(scope, arg.id)
            if bound is not None:
                return bound, ""
        return None, f"name `{arg.id}` not bound to a string constant"
    return None, "dynamic kind expression"


def _enclosing_node(tree: ast.AST, target: ast.AST) -> Optional[ast.AST]:
    line = getattr(target, "lineno", None)
    if line is None:
        return None
    best, best_span = None, None
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        hi = max((getattr(n, "lineno", node.lineno)
                  for n in ast.walk(node)), default=node.lineno)
        if node.lineno <= line <= hi:
            span = hi - node.lineno
            if best_span is None or span <= best_span:
                best, best_span = node, span
    return best


def _string_binding(scope: ast.AST, name: str) -> Optional[str]:
    value: Optional[str] = None
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant) and isinstance(
                    node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    value = node.value.value
    return value


# --------------------------------------------------------------------------
# Rule 2: metric-kind
# --------------------------------------------------------------------------

class MetricKindRule:
    """Every ``.log(...)`` kind must be a member of
    ``utils.metrics.KINDS``, resolved statically. Supersedes the PR 4
    grep test: the AST resolver also follows names bound to string
    constants and rejects f-strings/dynamic expressions the grep could
    not see."""

    name = "metric-kind"

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        kinds = registered_kinds(files)
        if not kinds:
            return []
        findings: List[Finding] = []
        for sf in files:
            m = ModuleInfo(sf)
            for call, kind, reason in _metric_log_calls(m):
                if kind is not None:
                    if kind not in kinds:
                        findings.append(_finding(
                            self.name, sf, call,
                            f"unregistered metrics kind {kind!r} — add "
                            "it to gtopkssgd_tpu.utils.metrics.KINDS"))
                else:
                    findings.append(_finding(
                        self.name, sf, call,
                        f"metrics kind is not statically resolvable "
                        f"({reason}) — use a registered literal"))
        return findings


# --------------------------------------------------------------------------
# Rule 3: exit-code
# --------------------------------------------------------------------------

class ExitCodeRule:
    """Process exit codes are a cross-tool contract (drivers and retry
    loops classify runs by rc without parsing logs), so every literal
    ``sys.exit`` / ``SystemExit`` / ``os._exit`` code must come from the
    single-source registry ``gtopkssgd_tpu/exit_codes.py`` — and no
    module may mint its own ``*_EXIT_CODE`` constant outside it."""

    name = "exit-code"

    _EXIT_CALLS = {"sys.exit", "os._exit", "SystemExit", "exit"}
    _CONST_RE = re.compile(r"(^EXIT_|_EXIT_CODE$|^EXITCODE)")

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        registry = exit_code_registry(files)
        findings: List[Finding] = []
        for code, names in sorted(registry.items()):
            if len(names) > 1:
                reg = [sf for sf in files
                       if sf.rel.endswith("exit_codes.py")]
                sf = reg[0] if reg else files[0]
                findings.append(Finding(
                    rule=self.name, path=sf.rel, line=1, col=0,
                    message=f"exit-code collision: {sorted(names)} all "
                            f"map to {code}",
                    symbol="<registry>", snippet=str(code)))
        known = set(registry)
        for sf in files:
            m = ModuleInfo(sf)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    full = m.full_name(node.func)
                    if full in self._EXIT_CALLS and node.args:
                        code = _int_literal(node.args[0])
                        if code is not None and code not in known:
                            findings.append(_finding(
                                self.name, sf, node,
                                f"exit code {code} is not in the "
                                "gtopkssgd_tpu.exit_codes registry"))
                elif isinstance(node, ast.Assign):
                    if sf.rel.endswith("exit_codes.py"):
                        continue
                    code = _int_literal(node.value)
                    if code is None:
                        continue
                    for t in node.targets:
                        if isinstance(t, ast.Name) and self._CONST_RE.search(
                                t.id):
                            findings.append(_finding(
                                self.name, sf, node,
                                f"exit-code constant `{t.id} = {code}` "
                                "defined outside gtopkssgd_tpu/"
                                "exit_codes.py — import it from the "
                                "registry instead"))
        return findings


def _int_literal(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _int_literal(node.operand)
        return -inner if inner is not None else None
    return None


# --------------------------------------------------------------------------
# Rule 4: codec-wire
# --------------------------------------------------------------------------

class CodecWireRule:
    """Every sparse (vals, idx) exchange in ``parallel/`` — and in
    ``optimizer.py``, where the bucketed layerwise path concatenates
    each bucket's leaves and merges them — must flow through the wire
    codec (``codec.encode`` / the merge tree's ``ship()`` /
    ``sparse_allreduce``, whose internals are themselves scanned), so
    no collective can silently bypass the wire format and break
    cross-rank bit-identity. Dense payloads (ici psum, the dense
    baseline, grad-norm pmeans) are exempt — the codec applies to
    sparse sets only. Every POSITIONAL operand is scanned, not just
    the leading one, and ``all_to_all`` is in the collective set: the
    balanced schedule (and any future plan member the planner makes
    additive) may pass its payload in a non-leading position or
    scatter via all_to_all, and a schedule that dodges the codec
    dodges the whole bit-identity audit. The sparse-name pattern also
    matches the bucketed path's per-bucket buffers (``vals_b``,
    ``idx_b``, plural ``_list`` forms), so a future bucket-concat
    exchange shipped raw is flagged the same as a flat one."""

    name = "codec-wire"

    _COLLECTIVES = {"lax.ppermute", "jax.lax.ppermute",
                    "lax.all_gather", "jax.lax.all_gather",
                    "lax.all_to_all", "jax.lax.all_to_all",
                    "lax.psum", "jax.lax.psum",
                    "lax.psum_scatter", "jax.lax.psum_scatter"}
    _SPARSE_NAME = re.compile(
        r"(^|_)(vals|idx|indices|values)(_b|_list)?$", re.IGNORECASE)
    _SCANNED = ("parallel/", "optimizer.py")

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        findings: List[Finding] = []
        for sf in files:
            if not any(part in sf.rel for part in self._SCANNED):
                continue
            m = ModuleInfo(sf)
            for fi in m.funcs:
                sanctioned = self._wire_names(fi)
                for node in own_statements(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    if m.full_name(node.func) not in self._COLLECTIVES:
                        continue
                    if not node.args:
                        continue
                    names = {n.id for arg in node.args
                             for n in ast.walk(arg)
                             if isinstance(n, ast.Name)}
                    if names & sanctioned:
                        continue  # ships codec.encode output
                    sparse = sorted(
                        n for n in names if self._SPARSE_NAME.search(n))
                    if sparse:
                        findings.append(_finding(
                            self.name, sf, node,
                            f"raw collective ships sparse payload "
                            f"({', '.join(sparse)}) without "
                            "codec.encode/ship() — every sparse "
                            "exchange must go through the wire codec",
                            symbol=fi.qualname))
        return findings

    def _wire_names(self, fi: FuncInfo) -> Set[str]:
        """Names holding codec.encode output (directly, via unpacking,
        or iterated element-wise) — the sanctioned wire buffers."""
        sanctioned: Set[str] = set()

        def rhs_is_wire(value: ast.AST) -> bool:
            for n in ast.walk(value):
                if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute) and n.func.attr == "encode":
                    return True
                if isinstance(n, ast.Name) and n.id in sanctioned:
                    return True
            return False

        changed = True
        while changed:
            changed = False
            for node in own_statements(fi.node):
                pairs: List[Tuple[ast.AST, ast.AST]] = []
                if isinstance(node, ast.Assign):
                    pairs = [(t, node.value) for t in node.targets]
                elif isinstance(node, ast.For):
                    pairs = [(node.target, node.iter)]
                elif isinstance(node, ast.comprehension):
                    pairs = [(node.target, node.iter)]
                for target, value in pairs:
                    if not rhs_is_wire(value):
                        continue
                    for leaf in ast.walk(target):
                        if isinstance(leaf, ast.Name) \
                                and leaf.id not in sanctioned:
                            sanctioned.add(leaf.id)
                            changed = True
        return sanctioned


# --------------------------------------------------------------------------
# Rule 5: durable-event
# --------------------------------------------------------------------------

class DurableEventRule:
    """Records that exist to survive a hard kill — anomaly ``event``s,
    injected-fault ``inject`` firings, ``recovery`` actions, comm-model
    ``calib`` refits, ``regress``/``overlap`` evidence rows, and
    ``critpath`` stage-interval records (the post-mortem "which stage
    bounded the last step" evidence) — must be fsync'd at the call
    site: ``.log(kind, flush=True, ...)``. Line buffering alone only
    reaches the OS, and these kinds are exactly the ones read back
    after a crash."""

    name = "durable-event"

    DURABLE_KINDS = {"event", "inject", "recovery", "calib", "regress",
                     "compile", "overlap", "critpath", "goodput",
                     "linkmap", "forecast", "resize"}

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        findings: List[Finding] = []
        for sf in files:
            m = ModuleInfo(sf)
            for call, kind, _reason in _metric_log_calls(m):
                if kind not in self.DURABLE_KINDS:
                    continue
                flush = next((kw.value for kw in call.keywords
                              if kw.arg == "flush"), None)
                ok = (isinstance(flush, ast.Constant)
                      and flush.value is True)
                if not ok:
                    findings.append(_finding(
                        self.name, sf, call,
                        f"durable kind {kind!r} logged without "
                        "flush=True — the record must be fsync'd to "
                        "survive a hard kill"))
        return findings


# --------------------------------------------------------------------------
# Rule 6: event-rule
# --------------------------------------------------------------------------

class EventRuleRule:
    """Every anomaly-event rule name stamped at an emit site must be a
    member of ``obs.events.RULES`` — the event-plane mirror of the
    metric-kind rule. ``_emit`` already rejects unregistered names at
    runtime; this rule catches the typo before any run, at the two
    static shapes emit sites take: a dict literal with a ``"rule"`` key
    (the monitor's own event records) and the first argument of a local
    ``fire(...)`` helper (the threshold-rule bodies)."""

    name = "event-rule"

    def run(self, files: Sequence[SourceFile]) -> List[Finding]:
        rules = registered_event_rules(files)
        if not rules:
            return []
        findings: List[Finding] = []
        for sf in files:
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Dict):
                    for key, val in zip(node.keys, node.values):
                        if (isinstance(key, ast.Constant)
                                and key.value == "rule"
                                and isinstance(val, ast.Constant)
                                and isinstance(val.value, str)
                                and val.value not in rules):
                            findings.append(_finding(
                                self.name, sf, node,
                                f"unregistered event rule "
                                f"{val.value!r} — add it to "
                                "gtopkssgd_tpu.obs.events.RULES (and "
                                "the README event table)"))
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "fire" and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        and node.args[0].value not in rules):
                    findings.append(_finding(
                        self.name, sf, node,
                        f"unregistered event rule "
                        f"{node.args[0].value!r} — add it to "
                        "gtopkssgd_tpu.obs.events.RULES (and the "
                        "README event table)"))
        return findings


ALL_RULES = (
    HostSyncInJitRule(),
    MetricKindRule(),
    ExitCodeRule(),
    CodecWireRule(),
    DurableEventRule(),
    EventRuleRule(),
)

RULES_BY_NAME = {r.name: r for r in ALL_RULES}
