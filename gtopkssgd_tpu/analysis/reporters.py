"""graftlint output formats: human text and machine JSON."""

from __future__ import annotations

import json
from typing import IO

from gtopkssgd_tpu.analysis.engine import Result


def text_report(result: Result, out: IO[str], verbose: bool = False) -> None:
    for f in result.findings:
        out.write(f"{f.location()}: [{f.rule}] {f.message}\n")
        if f.snippet:
            out.write(f"    {f.snippet}\n")
    if verbose:
        for f in result.baselined:
            out.write(f"{f.location()}: [{f.rule}] baselined: "
                      f"{f.message}\n")
        for f in result.suppressed:
            out.write(f"{f.location()}: [{f.rule}] suppressed: "
                      f"{f.message}\n")
    for key in result.stale_baseline:
        out.write(f"stale baseline entry (no longer fires): {key}\n")
    out.write(
        f"graftlint: {result.files_scanned} files, "
        f"{len(result.findings)} finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{len(result.suppressed)} suppressed"
        + (f", {len(result.stale_baseline)} stale baseline entr"
           f"{'y' if len(result.stale_baseline) == 1 else 'ies'}"
           if result.stale_baseline else "")
        + "\n")


def json_report(result: Result, out: IO[str]) -> None:
    def rows(findings):
        return [{
            "rule": f.rule, "path": f.path, "line": f.line,
            "col": f.col, "message": f.message, "symbol": f.symbol,
            "snippet": f.snippet,
        } for f in findings]

    json.dump({
        "findings": rows(result.findings),
        "baselined": rows(result.baselined),
        "suppressed": rows(result.suppressed),
        "stale_baseline": result.stale_baseline,
        "files_scanned": result.files_scanned,
    }, out, indent=1, sort_keys=True)
    out.write("\n")
