"""graftlint — stdlib-``ast`` static analysis for this package's
load-bearing conventions.

The codebase has a growing set of invariants that no type checker or
unit test can see whole-program: nothing inside the jitted hot path may
force a host sync (the dispatch-stall watchdog exists because one did),
every ``.log(`` kind must be registered in ``utils.metrics.KINDS``,
every process exit code must come from the ``gtopkssgd_tpu.exit_codes``
registry, every sparse (vals, idx) exchange in ``parallel/`` must flow
through the wire codec, and durable record kinds must be fsync'd.
graftlint checks all of them from source alone — no JAX import, no
device, runs in seconds — so the wire path stays auditable while the
on-chip tunnel is down (the same "correctness without silicon" posture
EQuARX-style quantized collectives argue for).

Usage::

    python -m gtopkssgd_tpu.analysis gtopkssgd_tpu/ [benchmarks/ ...]
        [--json] [--baseline PATH] [--write-baseline PATH]
        [--rule RULE ...] [--list-rules]

Exit codes (registered in gtopkssgd_tpu.exit_codes): 0 = clean (every
finding suppressed or baselined), 1 = non-baselined findings, 2 = usage.

Suppressions: append ``# graftlint: disable=RULE[,RULE|all]`` to the
flagged line (or the line directly above it). Suppressions are for
reviewed false positives — say why in the same comment.

Baseline: grandfathered findings live in a committed JSON file
(``graftlint_baseline.json`` at the repo root); entries match on
(rule, path, enclosing function, flagged source) so they survive line
drift. ``--write-baseline`` regenerates it; review the diff like code.
"""

from gtopkssgd_tpu.analysis.engine import (  # noqa: F401
    Finding,
    analyze,
    load_baseline,
    run,
)
from gtopkssgd_tpu.analysis.rules import ALL_RULES  # noqa: F401

__all__ = ["Finding", "analyze", "load_baseline", "run", "ALL_RULES"]
