"""Benchmark harness (reference C9/§5: the throughput logging + the paper's
forward/backward/compress/comm decomposition, which is its own analysis
axis — Fig. breakdowns in arXiv:1901.04359).

Two measurements:

  * ``measure_throughput`` — the production fused step (everything in one
    jitted SPMD program) timed end to end. This is the honest number: XLA
    overlaps compression/comm/compute, which host timers cannot decompose.
  * ``measure_breakdown`` — each phase jitted SEPARATELY (forward+backward /
    compress / collective / apply) and timed with device sync. The sum
    exceeds the fused step time (no overlap, extra boundaries) — the split
    is for analysis, exactly like the reference's timer dicts.

Batches are fixed and device-resident: these measure the framework step,
not host input pipelines.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.compression import get_compressor
from gtopkssgd_tpu.models import get_model
from gtopkssgd_tpu.modes import DENSE_MODES, HIER_MODES
from gtopkssgd_tpu.optimizer import gtopk_sgd
from gtopkssgd_tpu.ops import scatter_add_dense
from gtopkssgd_tpu.parallel import (
    comm_bytes_per_step,
    make_mesh,
    sparse_allreduce,
)
from gtopkssgd_tpu.obs import Tracer
from gtopkssgd_tpu.obs.memwatch import compiled_flops
from gtopkssgd_tpu.utils import (
    safe_donate,
    sync_round_trip_seconds,
    timed_window,
    true_sync,
)

# Module-level tracer: every measured window runs inside a named span, so a
# jax.profiler capture of a bench run (e.g. under benchmarks/profile_step)
# shows which phase each device region belongs to. No metrics sink — the
# bench emits its own JSON artifacts; the spans are for trace correlation.
_TRACER = Tracer()


@dataclasses.dataclass
class BenchConfig:
    dnn: str = "resnet50"
    batch_size: int = 128
    steps: int = 40              # breakdown mode: fixed step count
    min_seconds: float = 2.0     # throughput mode: time-based window
    density: float = 0.001
    dtype: str = "bfloat16"
    topk_method: str = "auto"
    nworkers: int = 0  # 0 = all devices
    hier_ici: int = 1  # gtopk_hier: devices per ICI slice
    s2d: bool = False  # resnet50: MXU-friendly space-to-depth stem
    momentum_correction: bool = False  # DGC velocity-before-selection


# Peak dense matmul throughput per chip (bf16), for MFU. Keys match
# jax.devices()[0].device_kind prefixes; unknown kinds report mfu=None
# rather than a made-up number.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}


def _peak_flops_per_chip() -> Optional[float]:
    kind = jax.devices()[0].device_kind
    for prefix, peak in PEAK_FLOPS.items():
        if kind.startswith(prefix):
            return peak
    return None


# Per-step FLOPs for MFU come from the SAME cost_analysis extraction
# path as the obs "compile" records (obs/memwatch.py) — one normalizer
# for the dict/list return-shape drift across jax versions, so bench
# and obs can never disagree on what XLA counted.
_compiled_flops = compiled_flops


def _setup(cfg: BenchConfig, mode: Optional[str], density: float):
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    model, spec = get_model(cfg.dnn, dtype=dtype, space_to_depth=cfg.s2d)
    rng = jax.random.PRNGKey(0)
    shape = (cfg.batch_size,) + tuple(spec.example_shape)
    variables = model.init(
        {"params": rng, "dropout": rng}, jnp.zeros((1,) + shape[1:])
    )
    tx = gtopk_sgd(
        0.1, momentum=0.9, compression=mode, density=density,
        topk_method=cfg.topk_method, axis_name="dp",
        hier_ici_size=cfg.hier_ici if mode in HIER_MODES else 1,
        # The dense baseline arm of a correction bench reuses this cfg;
        # dense IS classic momentum already (gtopk_sgd raises on the
        # combination), so the knob applies to the sparse arm only.
        momentum_correction=(cfg.momentum_correction
                             and mode not in DENSE_MODES),
    )
    return model, spec, variables, tx, shape


def _timeit(fn: Callable, args, steps: int) -> float:
    """Mean seconds per call via the shared honest timing loop
    (utils/timers.py::timed_window: back-to-back dispatch, ONE D2H fence —
    block_until_ready lies on the tunneled platform — round trip
    subtracted, window grown until it dwarfs the round trip). The device
    executes every enqueued launch in order, so fencing the last output
    waits for all of them.
    """
    out = fn(*args)
    rtt = sync_round_trip_seconds(out)

    def chunk(c):
        o = out
        for _ in range(c):
            o = fn(*args)
        true_sync(o)

    sec, _ = timed_window(chunk, rtt, 0.5, steps)
    return sec


def time_compiled_step(compiled, state, batch, min_seconds: float):
    """The one honest timing loop for a compiled ``state, aux = f(state,
    batch)`` step: 3 warmup steps, a D2H round-trip fence (true_sync —
    block_until_ready acks before execution on the tunneled platform),
    then a >= min_seconds window whose clock stops only after the FULL
    final state is executed, rtt subtracted (utils/timers.py discipline).
    Shared by measure_throughput and benchmarks/mfu_ablation.py so the
    protocol cannot drift between artifacts. Returns (sec_per_step,
    steps_timed, final_state)."""
    for _ in range(3):
        state, _ = compiled(state, batch)
    rtt = sync_round_trip_seconds(state)
    box = [state]

    def chunk(c):
        s = box[0]
        for _ in range(c):
            s, _ = compiled(s, batch)
        true_sync(s)
        box[0] = s

    sec, steps = timed_window(chunk, rtt, min_seconds, 8)
    return sec, steps, box[0]


def measure_throughput(cfg: BenchConfig, mode: Optional[str],
                       density: float) -> Dict[str, float]:
    """Fused-step images/sec/chip for one (mode, density) point.

    Measurement discipline (round-1 lesson: a 40-step window blocked only
    on `loss` — which does not depend on the param update — produced a
    dispatch-dominated, physically implausible number):

      * the timed window is TIME-based (>= cfg.min_seconds), not a fixed
        step count, so it is orders of magnitude above dispatch noise;
      * the clock stops only after a device-to-host read fences the FULL
        updated state (params + opt state incl. residual) — NOT
        jax.block_until_ready, which on the tunneled platform acks before
        execution (utils/timers.py::true_sync) — so every dispatched
        step's compute, including the collective and scatter-apply, is
        inside the window, and the one fixed round trip is subtracted;
      * per-step FLOPs come from the compiled executable's own
        cost_analysis, giving achieved FLOP/s and MFU vs the chip's peak.
    """
    from gtopkssgd_tpu.optimizer import (
        GTopKSGDState,
        expand_residual_per_device,
    )

    p = cfg.nworkers or jax.device_count()
    mesh = make_mesh(p)
    model, spec, variables, tx, shape = _setup(cfg, mode, density)
    has_bn = spec.has_batchnorm
    classes = 10 if spec.dataset == "cifar10" else 1000
    rng = jax.random.PRNGKey(1)
    x = jax.random.normal(rng, (p,) + shape)
    y = jax.random.randint(rng, (p, cfg.batch_size), 0, classes)
    params = variables["params"]
    bs = variables.get("batch_stats", {})

    def step(state, batch):
        params, bstats, opt_state = state
        # residual is per-device [1, ...] inside the block (same convention
        # as the trainer) — strip for the transform, restore on the way
        # out; tree.map covers the layerwise per-leaf tuple too
        opt_state = opt_state._replace(
            residual=jax.tree.map(lambda r: r[0], opt_state.residual))
        xb, yb = jax.tree.map(lambda b: b[0], batch)

        def loss_fn(params):
            v = {"params": params}
            if has_bn:
                v["batch_stats"] = bstats
            out = model.apply(v, xb, train=True,
                              mutable=["batch_stats"] if has_bn else [],
                              rngs={"dropout": jax.random.PRNGKey(0)})
            logits, nbs = out if has_bn else (out, bstats)
            if has_bn:
                nbs = nbs["batch_stats"]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean(), nbs

        (loss, nbs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        opt_state = opt_state._replace(
            residual=jax.tree.map(lambda r: r[None], opt_state.residual))
        return (params, nbs, opt_state), lax.pmean(loss, "dp")

    state_spec = (P(), P(), GTopKSGDState(count=P(), residual=P("dp"),
                                          inner=P()))
    fn = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=(state_spec, P("dp")),
            out_specs=(state_spec, P()), check_vma=False,
        ),
        donate_argnums=safe_donate(0),
    )
    opt0 = expand_residual_per_device(jax.jit(tx.init)(params), p, mesh)
    state = (params, bs, opt0)
    batch = (x, y)

    compiled = fn.lower(state, batch).compile()
    flops_per_step = _compiled_flops(compiled)
    with _TRACER.span("bench/throughput", mode=mode or "dense"):
        sec, steps, _ = time_compiled_step(compiled, state, batch,
                                           cfg.min_seconds)

    from gtopkssgd_tpu.optimizer import wire_k

    leaf_sizes = tuple(a.size for a in jax.tree.leaves(params))
    n = sum(leaf_sizes)
    # wire_k owns the communicated-set definition (incl. the layerwise
    # per-leaf ceil rounding that can exceed the flat ceil(rho*N)).
    k = wire_k(mode, density, n, leaf_sizes)
    peak = _peak_flops_per_chip()
    # cost_analysis reports PER-DEVICE flops for an SPMD-partitioned module
    # (verified empirically on a 4-device mesh), so this is already /chip.
    achieved = flops_per_step / sec if flops_per_step else None
    return {
        "mode": mode or "dense",
        "density": density,
        "sec_per_step": sec,
        "images_per_sec_per_chip": cfg.batch_size / sec,
        "steps_timed": steps,
        "window_seconds": sec * steps,
        "flops_per_step": flops_per_step,
        "achieved_tflops_per_chip": (
            achieved / 1e12 if achieved is not None else None
        ),
        "mfu": (achieved / peak if achieved is not None and peak else None),
        "comm_bytes_model": comm_bytes_per_step(
            mode, n, k, p,
            ici_size=cfg.hier_ici if mode in HIER_MODES else 1,
        ),
        "num_params": n,
        "nworkers": p,
    }


def _make_fwd_bwd(model, has_bn, bstats, xb, yb):
    """Shared grad closure for both breakdown paths (flat ravels on top)."""
    def fwd_bwd(params):
        def loss_fn(params):
            v = {"params": params}
            if has_bn:
                v["batch_stats"] = bstats
            out = model.apply(v, xb, train=True,
                              mutable=["batch_stats"] if has_bn else [],
                              rngs={"dropout": jax.random.PRNGKey(0)})
            logits = out[0] if has_bn else out
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()
        _, grads = jax.value_and_grad(loss_fn)(params)
        return grads
    return fwd_bwd


def _distinct_sparse_sets(vals, idx, p: int, n: int):
    """Per-device DISTINCT (vals, idx) stacks for timing the collective:
    replicating one set to every device would hand the merge its cheapest
    case (all duplicates); real steps merge mostly-disjoint index sets."""
    keys = jax.random.split(jax.random.PRNGKey(2), p)
    valss = jnp.stack([
        vals * jax.random.normal(kk, vals.shape) for kk in keys])
    idxs = jnp.stack([
        jax.random.randint(kk, idx.shape, 0, n, jnp.int32) for kk in keys])
    return valss, idxs


def measure_breakdown(cfg: BenchConfig, mode: Optional[str],
                      density: float) -> Dict[str, float]:
    """Per-phase seconds (forward+backward / compress / comm / apply), each
    jitted and synced separately — the reference's timer-dict decomposition."""
    from gtopkssgd_tpu.modes import LAYERWISE_MODES

    if mode in LAYERWISE_MODES:
        return _measure_breakdown_layerwise(cfg, mode, density)
    p = cfg.nworkers or jax.device_count()
    mesh = make_mesh(p)
    model, spec, variables, tx, shape = _setup(cfg, mode, density)
    has_bn = spec.has_batchnorm
    classes = 10 if spec.dataset == "cifar10" else 1000
    rng = jax.random.PRNGKey(1)
    xb = jax.random.normal(rng, shape)
    yb = jax.random.randint(rng, (cfg.batch_size,), 0, classes)
    params = variables["params"]
    bstats = variables.get("batch_stats", {})
    from jax.flatten_util import ravel_pytree

    flat0, unravel = ravel_pytree(params)
    n = flat0.shape[0]
    dense_mode = mode in DENSE_MODES
    compressor = get_compressor(mode, density, cfg.topk_method)
    k = compressor.k(n)

    grads_fn = _make_fwd_bwd(model, has_bn, bstats, xb, yb)

    def fwd_bwd(params):
        return ravel_pytree(grads_fn(params))[0]

    def compress(flat, residual):
        acc = compressor.accumulate(flat, residual)
        # Unfused operands let the twostage kernel fold the accumulate
        # into its stage-1 pass (no-op for the other methods).
        return compressor.compress(acc, grad=flat, residual=residual)

    hier_ici = cfg.hier_ici if mode in HIER_MODES else 1

    def _sparse_tail(v, i):
        r, gi, _ = sparse_allreduce(
            mode, v[0], i[0], k=k, n=n, axis_name="dp", axis_size=p,
            ici_size=hier_ici,
        )
        if gi is None:
            return r[None], jnp.zeros((1, 1), jnp.int32)
        return r[None], gi[None]

    if hier_ici > 1:
        # Hierarchical comm body: both communication levels are charged to
        # this phase — the dense within-slice psum on the flat gradient
        # (ICI) plus the cross-slice tree on the sparse sets (DCN). The
        # psum result must feed an OUTPUT or XLA dead-code-eliminates the
        # whole level-1 collective; a scalar checksum keeps it live (one
        # extra O(N) read — noise next to the psum itself). Non-hier modes
        # use the 2-arg body: threading the O(p*N) flats into their timed
        # call would add a per-call reshard they never pay in production.
        from gtopkssgd_tpu.parallel import ici_dense_psum

        def _sparse_body(f, v, i):
            f2 = ici_dense_psum(f[0], axis_name="dp", axis_size=p,
                                ici_size=hier_ici)
            r, gi = _sparse_tail(v, i)
            return r, gi, f2.sum()[None, None]

        comm_in_specs = (P("dp"), P("dp"), P("dp"))
        comm_out_specs = (P("dp"), P("dp"), P("dp"))
    else:
        _sparse_body = _sparse_tail
        comm_in_specs = (P("dp"), P("dp"))
        comm_out_specs = (P("dp"), P("dp"))

    # jit ONCE outside the timed call — rebuilding the jit per call would
    # time retracing, not the collective.
    comm_gtopk = jax.jit(jax.shard_map(
        _sparse_body, mesh=mesh, in_specs=comm_in_specs,
        out_specs=comm_out_specs, check_vma=False,
    ))
    comm_dense = jax.jit(jax.shard_map(
        lambda f: lax.psum(f[0], "dp")[None], mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp"), check_vma=False,
    ))

    def apply_updates(params, dense_grad):
        return optax.apply_updates(
            params, jax.tree.map(lambda g: -0.1 * g, unravel(dense_grad))
        )

    res: Dict[str, float] = {"mode": mode or "dense", "density": density}
    jf = jax.jit(fwd_bwd)
    flat = jf(params)
    with _TRACER.span("bench/forward_backward"):
        res["forward_backward"] = _timeit(jf, (params,), cfg.steps)
    if dense_mode:
        flats = jnp.broadcast_to(flat, (p,) + flat.shape)
        res["compress"] = 0.0
        with _TRACER.span("bench/comm"):
            res["comm"] = _timeit(comm_dense, (flats,), cfg.steps)
        dense_grad = flat
    else:
        residual = compressor.init_residual(n)
        jc = jax.jit(compress)
        vals, idx, _ = jc(flat, residual)
        with _TRACER.span("bench/compress"):
            res["compress"] = _timeit(jc, (flat, residual), cfg.steps)
        valss, idxs = _distinct_sparse_sets(vals, idx, p, n)
        if hier_ici > 1:
            # Pre-shard the per-device flats over 'dp' so the timed window
            # measures the collective, not a host->device reshard.
            from jax.sharding import NamedSharding

            flats = jax.device_put(
                jnp.broadcast_to(flat, (p,) + flat.shape),
                NamedSharding(mesh, P("dp")),
            )
            with _TRACER.span("bench/comm"):
                res["comm"] = _timeit(
                    comm_gtopk, (flats, valss, idxs), cfg.steps)
        else:
            with _TRACER.span("bench/comm"):
                res["comm"] = _timeit(comm_gtopk, (valss, idxs), cfg.steps)
        dense_grad = scatter_add_dense(n, idx, vals)
    ja = jax.jit(apply_updates)
    with _TRACER.span("bench/apply"):
        res["apply"] = _timeit(ja, (params, dense_grad), cfg.steps)
    res["sum"] = sum(v for q, v in res.items()
                     if q in ("forward_backward", "compress", "comm", "apply"))
    return res


def _measure_breakdown_layerwise(cfg: BenchConfig, mode: str,
                                 density: float) -> Dict[str, float]:
    """Phase split for the layerwise modes (round-2 verdict weak #7: the
    mode carrying the perf thesis had NO phase-level evidence path).

    Caveat stated in the numbers' names: in the PRODUCTION fused step the
    per-leaf accumulate/select/zero-out chains interleave with the
    backward epilogues (that non-serialization is the mode's entire
    reason to exist — optimizer.py layerwise docstring), so the isolated
    ``compress_per_leaf`` phase here measures work that the fused step
    overlaps, and ``sum`` is an upper bound exactly as it is for the flat
    decomposition (module docstring). The comparison that matters is
    compress_per_leaf vs the flat mode's serial ``compress`` at the same
    model/density — the tail the layerwise formulation removes."""
    from gtopkssgd_tpu.ops import k_for_density, select_topk

    p = cfg.nworkers or jax.device_count()
    mesh = make_mesh(p)
    model, spec, variables, tx, shape = _setup(cfg, mode, density)
    has_bn = spec.has_batchnorm
    classes = 10 if spec.dataset == "cifar10" else 1000
    rng = jax.random.PRNGKey(1)
    xb = jax.random.normal(rng, shape)
    yb = jax.random.randint(rng, (cfg.batch_size,), 0, classes)
    params = variables["params"]
    bstats = variables.get("batch_stats", {})

    leaves, treedef = jax.tree.flatten(params)
    sizes = [int(a.size) for a in leaves]
    ks = [k_for_density(s, density) for s in sizes]
    offsets, off = [], 0
    for s in sizes:
        offsets.append(off)
        off += s
    n, kk_total = off, sum(ks)

    fwd_bwd = _make_fwd_bwd(model, has_bn, bstats, xb, yb)

    def compress_per_leaf(grads, residual):
        flats = [g.reshape(-1) for g in jax.tree.leaves(grads)]
        accs = [f + r for f, r in zip(flats, residual)]
        sel = [select_topk(f, kl, cfg.topk_method, residual=r)
               for f, r, kl in zip(flats, residual, ks)]
        new_res = tuple(a.at[i].set(0.0, mode="drop")
                        for a, (_, i) in zip(accs, sel))
        vals = jnp.concatenate([v for v, _ in sel])
        idx = jnp.concatenate([
            (i + o).astype(jnp.int32) for (_, i), o in zip(sel, offsets)
        ])
        return vals, idx, new_res

    def _sparse_body(v, i):
        r, gi, _ = sparse_allreduce(
            mode, v[0], i[0], k=kk_total, n=n, axis_name="dp", axis_size=p)
        return r[None], gi[None]

    comm = jax.jit(jax.shard_map(
        _sparse_body, mesh=mesh, in_specs=(P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")), check_vma=False,
    ))

    def apply_updates(params, gvals, gidx):
        dense = scatter_add_dense(n, gidx, gvals) / p
        slices = [dense[o:o + s] for o, s in zip(offsets, sizes)]
        upd = treedef.unflatten([
            (-0.1 * d).reshape(leaf.shape)
            for d, leaf in zip(slices, leaves)
        ])
        return optax.apply_updates(params, upd)

    res: Dict[str, float] = {"mode": mode, "density": density,
                             "k_total": kk_total, "n": n}
    jf = jax.jit(fwd_bwd)
    grads = jf(params)
    with _TRACER.span("bench/forward_backward"):
        res["forward_backward"] = _timeit(jf, (params,), cfg.steps)
    residual = tuple(jnp.zeros((s,), jnp.float32) for s in sizes)
    jc = jax.jit(compress_per_leaf)
    vals, idx, _ = jc(grads, residual)
    with _TRACER.span("bench/compress_per_leaf"):
        res["compress_per_leaf"] = _timeit(jc, (grads, residual), cfg.steps)
    valss, idxs = _distinct_sparse_sets(vals, idx, p, n)
    with _TRACER.span("bench/comm"):
        res["comm"] = _timeit(comm, (valss, idxs), cfg.steps)
    gvals, gidx = comm(valss, idxs)
    ja = jax.jit(apply_updates)
    with _TRACER.span("bench/apply"):
        res["apply"] = _timeit(ja, (params, gvals[0], gidx[0]), cfg.steps)
    res["sum"] = sum(v for q, v in res.items()
                     if q in ("forward_backward", "compress_per_leaf",
                              "comm", "apply"))
    return res


def attr_from_breakdown(breakdown: Dict[str, float]) -> Dict[str, float]:
    """The paper's three-term split from a measure_breakdown result —
    the HOST-measured counterpart of obs.trace_attr.attribute (which
    reads a device trace). Same record shape, so ``report attr`` and the
    gate's frac checks consume either source: forward_backward + apply →
    T_compute, compress(_per_leaf) → T_select, comm → T_comm. Subject to
    the breakdown's own caveat (isolated phases; the fused step overlaps
    them, so the split is an upper-bound decomposition)."""
    t = {
        "compute": (breakdown.get("forward_backward", 0.0)
                    + breakdown.get("apply", 0.0)),
        "select": (breakdown.get("compress", 0.0)
                   + breakdown.get("compress_per_leaf", 0.0)),
        "comm": breakdown.get("comm", 0.0),
    }
    total = sum(t.values())
    rec: Dict[str, float] = {
        "mode": breakdown.get("mode"),
        "source": "host_breakdown",
        "t_total_us": round(total * 1e6, 1),
    }
    for term, sec in t.items():
        rec[f"t_{term}_us"] = round(sec * 1e6, 1)
        rec[f"frac_{term}"] = round(sec / total, 6) if total else 0.0
    return rec
