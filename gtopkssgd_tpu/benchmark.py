"""Benchmark harness (reference C9/§5: the throughput logging + the paper's
forward/backward/compress/comm decomposition, which is its own analysis
axis — Fig. breakdowns in arXiv:1901.04359).

Two measurements:

  * ``measure_throughput`` — the production fused step (everything in one
    jitted SPMD program) timed end to end. This is the honest number: XLA
    overlaps compression/comm/compute, which host timers cannot decompose.
  * ``measure_breakdown`` — each phase jitted SEPARATELY (forward+backward /
    compress / collective / apply) and timed with device sync. The sum
    exceeds the fused step time (no overlap, extra boundaries) — the split
    is for analysis, exactly like the reference's timer dicts.

Batches are fixed and device-resident: these measure the framework step,
not host input pipelines.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from gtopkssgd_tpu.compression import get_compressor
from gtopkssgd_tpu.models import get_model
from gtopkssgd_tpu.modes import DENSE_MODES
from gtopkssgd_tpu.optimizer import gtopk_sgd
from gtopkssgd_tpu.ops import scatter_add_dense
from gtopkssgd_tpu.parallel import (
    comm_bytes_per_step,
    make_mesh,
    sparse_allreduce,
)


@dataclasses.dataclass
class BenchConfig:
    dnn: str = "resnet20"
    batch_size: int = 256
    steps: int = 40
    density: float = 0.001
    dtype: str = "bfloat16"
    topk_method: str = "auto"
    nworkers: int = 0  # 0 = all devices


def _setup(cfg: BenchConfig, mode: Optional[str], density: float):
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    model, spec = get_model(cfg.dnn, dtype=dtype)
    rng = jax.random.PRNGKey(0)
    shape = (cfg.batch_size,) + tuple(spec.example_shape)
    variables = model.init(
        {"params": rng, "dropout": rng}, jnp.zeros((1,) + shape[1:])
    )
    tx = gtopk_sgd(
        0.1, momentum=0.9, compression=mode, density=density,
        topk_method=cfg.topk_method, axis_name="dp",
    )
    return model, spec, variables, tx, shape


def _timeit(fn: Callable, args, steps: int) -> float:
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def measure_throughput(cfg: BenchConfig, mode: Optional[str],
                       density: float) -> Dict[str, float]:
    """Fused-step images/sec/chip for one (mode, density) point."""
    p = cfg.nworkers or jax.device_count()
    mesh = make_mesh(p)
    model, spec, variables, tx, shape = _setup(cfg, mode, density)
    has_bn = spec.has_batchnorm
    classes = 10 if spec.dataset == "cifar10" else 1000
    rng = jax.random.PRNGKey(1)
    x = jax.random.normal(rng, (p,) + shape)
    y = jax.random.randint(rng, (p, cfg.batch_size), 0, classes)
    params = variables["params"]
    bs = variables.get("batch_stats", {})

    def step(state, batch):
        params, bstats, opt_state = state
        xb, yb = jax.tree.map(lambda b: b[0], batch)

        def loss_fn(params):
            v = {"params": params}
            if has_bn:
                v["batch_stats"] = bstats
            out = model.apply(v, xb, train=True,
                              mutable=["batch_stats"] if has_bn else [],
                              rngs={"dropout": jax.random.PRNGKey(0)})
            logits, nbs = out if has_bn else (out, bstats)
            if has_bn:
                nbs = nbs["batch_stats"]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean(), nbs

        (loss, nbs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, nbs, opt_state), lax.pmean(loss, "dp")

    fn = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P("dp")), out_specs=(P(), P()),
        check_vma=False,
    ))
    state = (params, bs, jax.jit(tx.init)(params))

    def run(state):
        state, loss = fn(state, (x, y))
        return state, loss

    # warmup
    for _ in range(2):
        state, loss = run(state)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(cfg.steps):
        state, loss = run(state)
    jax.block_until_ready(loss)
    sec = (time.perf_counter() - t0) / cfg.steps
    n = sum(a.size for a in jax.tree.leaves(params))
    k = get_compressor(mode, density).k(n)
    return {
        "mode": mode or "dense",
        "density": density,
        "sec_per_step": sec,
        "images_per_sec_per_chip": cfg.batch_size / sec,
        "comm_bytes_model": comm_bytes_per_step(mode, n, k, p),
        "num_params": n,
        "nworkers": p,
    }


def measure_breakdown(cfg: BenchConfig, mode: Optional[str],
                      density: float) -> Dict[str, float]:
    """Per-phase seconds (forward+backward / compress / comm / apply), each
    jitted and synced separately — the reference's timer-dict decomposition."""
    p = cfg.nworkers or jax.device_count()
    mesh = make_mesh(p)
    model, spec, variables, tx, shape = _setup(cfg, mode, density)
    has_bn = spec.has_batchnorm
    classes = 10 if spec.dataset == "cifar10" else 1000
    rng = jax.random.PRNGKey(1)
    xb = jax.random.normal(rng, shape)
    yb = jax.random.randint(rng, (cfg.batch_size,), 0, classes)
    params = variables["params"]
    bstats = variables.get("batch_stats", {})
    from jax.flatten_util import ravel_pytree

    flat0, unravel = ravel_pytree(params)
    n = flat0.shape[0]
    dense_mode = mode in DENSE_MODES
    compressor = get_compressor(mode, density, cfg.topk_method)
    k = compressor.k(n)

    def fwd_bwd(params):
        def loss_fn(params):
            v = {"params": params}
            if has_bn:
                v["batch_stats"] = bstats
            out = model.apply(v, xb, train=True,
                              mutable=["batch_stats"] if has_bn else [],
                              rngs={"dropout": jax.random.PRNGKey(0)})
            logits = out[0] if has_bn else out
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()
        _, grads = jax.value_and_grad(loss_fn)(params)
        return ravel_pytree(grads)[0]

    def compress(flat, residual):
        acc = compressor.accumulate(flat, residual)
        return compressor.compress(acc)

    def _sparse_body(v, i):
        r, gi, _ = sparse_allreduce(
            mode, v[0], i[0], k=k, n=n, axis_name="dp", axis_size=p
        )
        if gi is None:
            return r[None], jnp.zeros((1, 1), jnp.int32)
        return r[None], gi[None]

    # jit ONCE outside the timed call — rebuilding the jit per call would
    # time retracing, not the collective.
    comm_gtopk = jax.jit(jax.shard_map(
        _sparse_body, mesh=mesh, in_specs=(P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")), check_vma=False,
    ))
    comm_dense = jax.jit(jax.shard_map(
        lambda f: lax.psum(f[0], "dp")[None], mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp"), check_vma=False,
    ))

    def apply_updates(params, dense_grad):
        return optax.apply_updates(
            params, jax.tree.map(lambda g: -0.1 * g, unravel(dense_grad))
        )

    res: Dict[str, float] = {"mode": mode or "dense", "density": density}
    jf = jax.jit(fwd_bwd)
    flat = jf(params)
    res["forward_backward"] = _timeit(jf, (params,), cfg.steps)
    if dense_mode:
        flats = jnp.broadcast_to(flat, (p,) + flat.shape)
        res["compress"] = 0.0
        res["comm"] = _timeit(comm_dense, (flats,), cfg.steps)
        dense_grad = flat
    else:
        residual = compressor.init_residual(n)
        jc = jax.jit(compress)
        vals, idx, _ = jc(flat, residual)
        res["compress"] = _timeit(jc, (flat, residual), cfg.steps)
        valss = jnp.broadcast_to(vals, (p,) + vals.shape)
        idxs = jnp.broadcast_to(idx, (p,) + idx.shape)
        res["comm"] = _timeit(comm_gtopk, (valss, idxs), cfg.steps)
        dense_grad = scatter_add_dense(n, idx, vals)
    ja = jax.jit(apply_updates)
    res["apply"] = _timeit(ja, (params, dense_grad), cfg.steps)
    res["sum"] = sum(v for q, v in res.items()
                     if q in ("forward_backward", "compress", "comm", "apply"))
    return res
