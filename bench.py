"""Benchmark: gTop-k S-SGD step throughput vs the dense-allreduce baseline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": R}

value = gtopk (rho=0.001) fused-train-step throughput per chip;
vs_baseline = ratio to the dense-psum baseline measured in the same run on
the same hardware — the reference's own headline comparison (paper: gTop-k
vs dense S-SGD scaling efficiency; BASELINE.json north star: ">= dense-
allreduce images/sec/chip").

The measured step is the full production path (forward + backward + error-
feedback compress + collective + SGD update) in one jitted SPMD program
over every visible chip, with fixed device-resident batches (isolates the
framework step from host input pipelines; benchmarks/sweep.py has the full
grid and the per-phase breakdown).

Usage: python bench.py [--dnn resnet20] [--batch-size 256] [--steps 40]
"""

from __future__ import annotations

import argparse
import json

import jax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dnn", default="resnet20")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--topk-method", default="auto")
    args = ap.parse_args()

    from gtopkssgd_tpu.benchmark import BenchConfig, measure_throughput

    cfg = BenchConfig(
        dnn=args.dnn, batch_size=args.batch_size, steps=args.steps,
        density=args.density, dtype=args.dtype, topk_method=args.topk_method,
    )
    gtopk = measure_throughput(cfg, "gtopk", args.density)
    dense = measure_throughput(cfg, "dense", 1.0)
    p = jax.device_count()
    print(json.dumps({
        "metric": f"{args.dnn}_gtopk_rho{args.density}_train_throughput"
                  f"_{p}chip",
        "value": round(gtopk["images_per_sec_per_chip"], 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            gtopk["images_per_sec_per_chip"]
            / dense["images_per_sec_per_chip"], 4
        ),
    }))


if __name__ == "__main__":
    main()
