"""Benchmark: gTop-k S-SGD step throughput vs the dense-allreduce baseline.

Prints ONE JSON line with the driver-required keys plus the supporting
absolute numbers that make the headline ratio auditable:

  metric       — "<dnn>_gtopk_rho<rho>_train_throughput_<P>chip"
  value        — gtopk (rho=0.001) images/sec/chip
  unit         — "images/sec/chip"
  vs_baseline  — value / dense-psum images/sec/chip, same run, same chip
  ...plus      — dense absolute throughput, step ms for both modes,
                 XLA-counted FLOPs/step, achieved TFLOP/s and MFU, device.

Default workload is the north-star one (BASELINE.md): ResNet-50 at
224x224, bf16, synthetic ImageNet shapes. The default --compression=auto
measures BOTH the flat gtopk and gtopk_layerwise (the round-2 serial-tail
fix) and headlines the faster one, with both absolutes in the output. On
ONE chip neither mode communicates, so the sparse mode = dense +
selection overhead and vs_baseline is expected to be <= 1.0; sparsity
pays off only when a network is in the path (the multi-chip sweep lives
in benchmarks/sweep.py).

The p=1 ratio measured through round 3 (~0.90 at bs=128 / 0.98 at
bs=256, bench_r3 artifact) was structural for the INDEX-SET formulation:
compress-chain reformulations all landed within noise in the fused step
(fused_variants artifact) because the scatter/gather through the flat
[N] vector serialized against the backward epilogue. Round 3 replaced
the p=1 selection with a threshold form (compress_by_threshold: one
top-k reduction for tau + elementwise masks, no scatter/gather) and made
BatchNorm emit the compute dtype (halving inter-conv HBM bytes for BOTH
modes); the before/after of these two changes is queued as the first
stage of benchmarks/onchip_queue.sh — the tunnel died before they could
be measured on silicon. Larger per-chip batch amortizes the fixed tail
but also drops the dense baseline's own throughput, so the default stays
at the batch both modes prefer.

The measured step is the full production path (forward + backward + error-
feedback compress + collective + SGD update) in one jitted SPMD program
over every visible chip, timed over a >= 2 s window that ends with a
block_until_ready on the FULL updated state (see
gtopkssgd_tpu/benchmark.py::measure_throughput for the discipline).

Usage: python bench.py [--dnn resnet50] [--batch-size 128] [--min-seconds 2]
"""

from __future__ import annotations

import argparse
import json
import sys


def _fail_fast_if_backend_dead(timeout_s: float = 180.0) -> None:
    """Exit with a diagnostic instead of hanging when the TPU tunnel is
    down: backend init blocks forever inside PJRT client creation in that
    state (observed when the axon relay died mid-round), which would hang
    the driver's bench step. The shared watchdog-deadline init bounds the
    wait at zero extra cost on the healthy path; a backend that
    initializes but ERRORS returns normally here and the real error
    surfaces from main()'s own first jax call."""
    import time

    from gtopkssgd_tpu.utils import init_backend_with_deadline

    t0 = time.monotonic()
    if init_backend_with_deadline(timeout_s):
        return
    # Leave a machine-readable record of the dead tunnel, not just rc=3:
    # the retry/post-mortem tooling reads these (same schema as
    # benchmarks/backend_probe.py, which onchip_retry.sh emits per
    # attempt).
    from benchmarks.backend_probe import append_jsonl, make_record

    rec = make_record(alive=False, timeout_s=timeout_s,
                      elapsed_s=time.monotonic() - t0, hung=True,
                      source="bench.py")
    print(json.dumps(rec, sort_keys=True), file=sys.stderr)
    try:
        append_jsonl(rec, "/tmp/backend_probe.jsonl")
    except OSError:
        pass
    print(f"bench.py: accelerator backend init still blocked after "
          f"{timeout_s:.0f}s (dead device tunnel?); refusing to hang — "
          "fix the tunnel and re-run", file=sys.stderr)
    print(_latest_onchip_artifact_note(), file=sys.stderr)
    raise SystemExit(_cpu_fallback_bench())


def _cpu_fallback_bench() -> int:
    """Dead-tunnel fallback: emit a fresh CPU/interpret-mode selection
    microbench line instead of only the backend-probe record (rc=3, no
    parsed data — the BENCH_r02..r05 shape). Runs benchmarks/topk_bench.py
    --cpu-fallback in a SUBPROCESS: this process's backend is poisoned (a
    daemon thread is still blocked inside PJRT client init), and the
    child must call force_cpu_mesh before its first backend touch. On
    success prints ONE driver-format JSON line headlining twostage-vs-
    exact selection recall at CIFAR scale (interpret-mode ms are not
    device numbers; recall and the one-pass op-size evidence are the
    comparable fields) and returns 0; if the fallback itself fails,
    returns the legacy 3 so the rc still signals a dead round."""
    import os
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    art = os.path.join(here, "benchmarks", "results",
                       "topk_bench_cpu_fallback.json")
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.topk_bench",
         "--cpu-fallback", "--out", art],
        cwd=here, capture_output=True, text=True, timeout=1800)
    if proc.returncode != 0:
        print("bench.py: cpu-fallback microbench failed "
              f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}",
              file=sys.stderr)
        return 3
    try:
        with open(art) as f:
            result = json.load(f)
        rows = {r["method"]: r for r in result["rows"]
                if r.get("error") is None}
        ts = rows["twostage"]
        exact = rows["exact"]
    except (OSError, KeyError, ValueError) as e:
        print(f"bench.py: cpu-fallback artifact unreadable: {e}",
              file=sys.stderr)
        return 3
    evidence = result.get("one_pass_evidence", {})
    print(json.dumps({
        "metric": (f"topk_twostage_recall_vs_exact_n{ts['n']}"
                   f"_rho{ts['density']}_cpu_fallback"),
        "value": ts["recall_vs_exact"],
        "unit": "recall",
        "vs_baseline": ts["recall_vs_exact"],  # exact recall == 1.0
        "backend": "cpu_fallback",
        "pallas_interpret": True,
        "twostage_ms_interpret": ts["ms"],
        "exact_ms_interpret": exact["ms"],
        "tau_twostage_mask_recall": rows.get(
            "tau_twostage", {}).get("recall_vs_exact"),
        "count_single_pass": evidence.get("single_pass"),
        "count_bucketize_passes_over_x": evidence.get(
            "bucketize_passes_over_x"),
        "count_vmap8_passes_over_x": evidence.get("vmap8_passes_over_x"),
        "artifact": os.path.relpath(art, here),
        "note": "dead tunnel: interpret-mode selection microbench; "
                "ms columns are NOT device numbers",
    }))
    return 0


def latest_bench_artifact_path():
    """Newest committed bench_r*.json in NUMERIC round order (a
    lexicographic sort would rank bench_r10 before bench_r2 and pin a
    stale round forever). Shared by the dead-tunnel note below and
    benchmarks/time_to_quality.py. Returns None if none exist."""
    import glob
    import os
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    paths = sorted(
        glob.glob(os.path.join(here, "benchmarks", "results",
                               "bench_r*.json")),
        key=lambda p: (int(m.group(1)) if
                       (m := re.search(r"bench_r(\d+)", p)) else -1, p))
    return paths[-1] if paths else None


def _latest_onchip_artifact_note() -> str:
    """Point a dead-tunnel failure at the round's real on-chip number.

    The driver records only this process's tail; when the tunnel is down
    at round end the bench number for the round lives in a committed
    artifact captured earlier in the round (the round-start queue drain).
    Name it, with its headline line, so BENCH_r0N.json self-documents
    where to look instead of reading as 'no measurement exists'.
    """
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    path = latest_bench_artifact_path()
    if path is None:
        return "bench.py: no committed on-chip bench artifact found"
    headline = ""
    try:
        with open(path) as f:
            art = json.load(f)
        # Artifacts keyed by batch-size blocks (bs128/bs256/...), each a
        # driver-format line; headline the first block found.
        for key in sorted(k for k in art if k.startswith("bs")):
            line = art[key]
            if isinstance(line, dict) and "value" in line:
                headline = " headline=" + json.dumps(
                    {k: line[k] for k in
                     ("metric", "value", "unit", "vs_baseline") if k in line},
                    sort_keys=True)
                break
    except Exception:
        pass
    return (f"bench.py: this round's on-chip record is the committed "
            f"artifact {os.path.relpath(path, here)}{headline}")


def main():
    _fail_fast_if_backend_dead()
    import jax
    from gtopkssgd_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser()
    ap.add_argument("--dnn", default="resnet50")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--min-seconds", type=float, default=2.0)
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--topk-method", default="auto")
    ap.add_argument("--s2d", action="store_true",
                    help="resnet50: space-to-depth stem (4x4x12 conv on "
                         "2x2 pixel blocks instead of 7x7x3 — a superset "
                         "of the 7x7 map, exact embedding pinned in "
                         "tests/test_models.py; MXU-friendly channel "
                         "width)")
    ap.add_argument("--momentum-correction", action="store_true",
                    help="DGC velocity-before-selection on the sparse "
                         "arm (the measured best cold-start config; "
                         "dense baseline arm is unaffected — it is "
                         "classic momentum already)")
    ap.add_argument("--attr-trace", default=None, metavar="DIR",
                    help="after the timed windows, re-run the headline "
                         "sparse mode under the profiler (Python tracer "
                         "off — obs.trace_attr.capture) and fold the "
                         "paper's T_compute/T_select/T_comm fractions "
                         "into the output JSON; the raw trace stays in "
                         "DIR for TensorBoard/Perfetto")
    ap.add_argument("--compression", default="auto",
                    help="sparse mode to benchmark against the dense "
                         "baseline (gtopk | gtopk_layerwise | allgather); "
                         "'auto' measures gtopk AND gtopk_layerwise and "
                         "headlines whichever is faster (round-2 verdict: "
                         "the serial-tail fix must show up in the "
                         "driver's number when it wins)")
    args = ap.parse_args()

    from gtopkssgd_tpu.benchmark import BenchConfig, measure_throughput

    cfg = BenchConfig(
        dnn=args.dnn, batch_size=args.batch_size,
        min_seconds=args.min_seconds, density=args.density,
        dtype=args.dtype, topk_method=args.topk_method, s2d=args.s2d,
        momentum_correction=args.momentum_correction,
    )
    if args.compression == "auto" and args.momentum_correction:
        # layerwise x correction is a measured-worse combination
        # (warmup_ab ablation; gtopk_sgd warns on it) — a corr bench
        # compares flat gtopk+corr vs dense only.
        args.compression = "gtopk"
    if args.compression == "auto":
        candidates = {
            m: measure_throughput(cfg, m, args.density)
            for m in ("gtopk", "gtopk_layerwise")
        }
        mode = max(candidates,
                   key=lambda m: candidates[m]["images_per_sec_per_chip"])
        gtopk = candidates[mode]
        alt = {f"{m}_images_per_sec_per_chip":
               round(r["images_per_sec_per_chip"], 2)
               for m, r in candidates.items()}
    else:
        mode = args.compression
        gtopk = measure_throughput(cfg, mode, args.density)
        alt = {}
    dense = measure_throughput(cfg, "dense", 1.0)
    attr = {}
    if args.attr_trace:
        # Everything is jit-cached by the measurements above, so the
        # traced window is pure execution — exactly what attribution
        # wants on the trace.
        from gtopkssgd_tpu.obs.trace_attr import attribute, capture

        with capture(args.attr_trace):
            measure_throughput(cfg, mode, args.density)
        rec = attribute(args.attr_trace, mode=mode)
        attr = {f"attr_{k}": rec[k] for k in
                ("source", "frac_compute", "frac_select", "frac_comm")}
    p = jax.device_count()

    def _r(v, nd=4):
        return round(v, nd) if isinstance(v, float) else v

    mode_label = mode + ("+corr" if args.momentum_correction else "")
    print(json.dumps({
        "metric": f"{args.dnn}_{mode_label}_rho{args.density}"
                  f"_train_throughput_{p}chip",
        "value": round(gtopk["images_per_sec_per_chip"], 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            gtopk["images_per_sec_per_chip"]
            / dense["images_per_sec_per_chip"], 4
        ),
        **alt,
        **attr,
        "dense_images_per_sec_per_chip": round(
            dense["images_per_sec_per_chip"], 2),
        "gtopk_step_ms": round(gtopk["sec_per_step"] * 1e3, 3),
        "dense_step_ms": round(dense["sec_per_step"] * 1e3, 3),
        "gtopk_steps_timed": gtopk["steps_timed"],
        "dense_steps_timed": dense["steps_timed"],
        "flops_per_step": gtopk["flops_per_step"],
        "gtopk_achieved_tflops_per_chip": _r(
            gtopk["achieved_tflops_per_chip"], 2),
        "dense_achieved_tflops_per_chip": _r(
            dense["achieved_tflops_per_chip"], 2),
        "gtopk_mfu": _r(gtopk["mfu"]),
        "dense_mfu": _r(dense["mfu"]),
        "num_params": gtopk["num_params"],
        "batch_size_per_chip": args.batch_size,
        "device_kind": jax.devices()[0].device_kind,
        "nchips": p,
    }))


if __name__ == "__main__":
    main()
