"""Benchmark: gTop-k S-SGD step throughput vs the dense-allreduce baseline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": R}

where value is the gtopk (rho=0.001) training-step throughput per chip and
vs_baseline is its ratio to the dense-psum baseline measured in the same
run on the same hardware — the reference's own headline comparison (paper:
gTop-k vs dense S-SGD scaling efficiency; BASELINE.json north star:
">= dense-allreduce images/sec/chip").

The measured step is the full production path: forward + backward +
error-feedback compress + collective + SGD update, jitted as one SPMD
program over every visible chip. Batches are device-resident and fixed so
the number isolates the framework/step pipeline, not host data generation
(the -D flag in dist_trainer measures the full input pipeline instead).

Usage: python bench.py [--dnn resnet20] [--batch-size 256] [--steps 40]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P


def build_step(model, tx, p, mesh, has_bn):
    def step(state, batch):
        params, bs, opt_state = state
        x, y = jax.tree.map(lambda b: b[0], batch)

        def loss_fn(params):
            variables = {"params": params}
            if has_bn:
                variables["batch_stats"] = bs
            out = model.apply(variables, x, train=True,
                              mutable=["batch_stats"] if has_bn else [],
                              rngs={"dropout": jax.random.PRNGKey(0)})
            logits, new_bs = out if has_bn else (out, bs)
            if has_bn:
                new_bs = new_bs["batch_stats"]
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            return loss, new_bs

        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if p > 1:
            loss = lax.pmean(loss, "dp")
            if has_bn:
                new_bs = jax.tree.map(lambda a: lax.pmean(a, "dp"), new_bs)
        return (params, new_bs, opt_state), loss

    if p == 1:
        return jax.jit(step, donate_argnums=0)
    return jax.jit(
        jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P("dp")),
            out_specs=(P(), P()),
            check_vma=False,
        ),
        donate_argnums=0,
    )


def measure(mode, density, args, mesh, p):
    from gtopkssgd_tpu.models import get_model
    from gtopkssgd_tpu.optimizer import gtopk_sgd

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    model, spec = get_model(args.dnn, dtype=dtype)
    has_bn = spec.has_batchnorm
    rng = jax.random.PRNGKey(0)
    shape = (args.batch_size,) + tuple(spec.example_shape)
    x1 = jax.random.normal(rng, (1,) + shape[1:])
    variables = model.init({"params": rng, "dropout": rng}, x1)
    tx = gtopk_sgd(
        0.1, momentum=0.9, compression=mode, density=density,
        topk_method=args.topk_method, axis_name="dp" if p > 1 else None,
    )
    params = variables["params"]
    bs = variables.get("batch_stats", {})
    state = (params, bs, jax.jit(tx.init)(params))
    classes = 10 if spec.dataset == "cifar10" else 1000
    x = jax.random.normal(rng, (p,) + shape)
    y = jax.random.randint(rng, (p, args.batch_size), 0, classes)
    step = build_step(model, tx, p, mesh, has_bn)
    # warmup (compile + 2 steps)
    for _ in range(3):
        state, loss = step(state, (x, y))
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, loss = step(state, (x, y))
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    imgs_per_sec = args.steps * args.batch_size * p / dt
    return imgs_per_sec / p  # per chip


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dnn", default="resnet20")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--density", type=float, default=0.001)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--topk-method", default="auto")
    args = ap.parse_args()

    from gtopkssgd_tpu.parallel import make_mesh

    p = jax.device_count()
    mesh = make_mesh(p)
    gtopk = measure("gtopk", args.density, args, mesh, p)
    dense = measure("dense", 1.0, args, mesh, p)
    print(json.dumps({
        "metric": f"{args.dnn}_gtopk_rho{args.density}_train_throughput"
                  f"_{p}chip",
        "value": round(gtopk, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(gtopk / dense, 4),
    }))


if __name__ == "__main__":
    main()
